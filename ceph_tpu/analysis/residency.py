"""tpusan's runtime arm: transfer counters, seams, and the
device-resident-section verifier.

The static rule (``rules_residency.check_d2h_in_resident_section``)
proves no *lexical* D2H sink sits inside a declared resident section.
This module closes the loop at runtime, the way ``analysis/runtime.py``
does for atomic sections -- the annotation is tested, not trusted:

* **Counters** -- every transfer the storage layer performs through the
  sanctioned seams (:func:`device_put` / :func:`device_get`, plus the
  direct ``note_h2d``/``note_d2h`` hooks at call sites that keep their
  raw jax spelling) lands in one process-wide
  :class:`ResidencyCounters` ledger: h2d/d2h ops and bytes.  JIT
  retraces ride the same ledger through a ``jax.monitoring`` listener
  counting XLA backend compiles (one event per compilation; cache hits
  emit nothing), so "no per-shape recompiles" is a number, not a vibe.
  ``bench.py`` snapshots the ledger around every stage and emits the
  deltas; the prometheus mgr module exposes the same counters as
  ``ceph_transfer_bytes_total{direction=...}`` / ``ceph_jit_retraces_total``.
* **Sections** -- :func:`resident_section` is the runtime guard paired
  with the ``# cephlint: device-resident-section`` comment markers
  (the static rule enforces the pairing).  Under tier-1 the global
  verifier runs in ``raise`` mode: a seam D2H inside an open section
  raises :class:`ResidencySectionError` at the offending call, and the
  section body additionally runs under
  ``jax.transfer_guard_device_to_host("disallow")`` so *implicit* D2H
  that bypasses the seams fails natively on a real device.  (The full
  ``transfer_guard("disallow")`` is deliberately NOT used: device-side
  slicing/arithmetic materializes index scalars as implicit H2D, which
  is legal inside a resident region.)  ``record`` mode detects the same
  seam violations without perturbing control flow -- the conftest hook
  fails the driving test, like atomic-section violations.  Escape
  hatch: ``CEPH_TPU_RESIDENCY_VERIFY=0`` (declared in OPTIONS as
  ``residency_verify``).

On a CPU backend the jax transfer guard cannot see D2H (host and
device memory are one, the copy is free), so under the cpu-fallback
tier-1 run the seams ARE the verifier; on TPU both layers are live.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, List, Optional

import numpy as np


class ResidencyCounters:
    """Process-wide transfer/retrace ledger (thread-safe)."""

    __slots__ = ("_lock", "h2d_ops", "h2d_bytes", "d2h_ops", "d2h_bytes",
                 "jit_retraces", "mesh_axes")

    def __init__(self):
        self._lock = threading.Lock()
        self.h2d_ops = 0
        self.h2d_bytes = 0
        self.d2h_ops = 0
        self.d2h_bytes = 0
        self.jit_retraces = 0
        #: per-mesh-axis sharded-dispatch accounting (the mesh data
        #: plane's slice of the ledger): axis name -> [dispatches,
        #: bytes placed along that axis].  Keyed dynamically so new
        #: axes (pg/shard/sub) need no schema change.
        self.mesh_axes: Dict[str, List[int]] = {}

    def note_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_ops += 1
            self.h2d_bytes += int(nbytes)

    def note_d2h(self, nbytes: int) -> None:
        with self._lock:
            self.d2h_ops += 1
            self.d2h_bytes += int(nbytes)

    def note_retrace(self) -> None:
        with self._lock:
            self.jit_retraces += 1

    def note_mesh(self, axis: str, nbytes: int) -> None:
        """One sharded dispatch placing ``nbytes`` along mesh ``axis``
        (the mesh plane calls this per axis of every SPMD encode/decode
        dispatch, so "how much work rides each mesh axis" is a ledger
        number like the transfer counters)."""
        with self._lock:
            ent = self.mesh_axes.setdefault(axis, [0, 0])
            ent[0] += 1
            ent[1] += int(nbytes)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = {
                "h2d_ops": self.h2d_ops,
                "h2d_bytes": self.h2d_bytes,
                "d2h_ops": self.d2h_ops,
                "d2h_bytes": self.d2h_bytes,
                "jit_retraces": self.jit_retraces,
            }
            for axis, (ops, nbytes) in self.mesh_axes.items():
                out[f"mesh_{axis}_dispatches"] = ops
                out[f"mesh_{axis}_bytes"] = nbytes
            return out

    @staticmethod
    def delta(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
        return {k: after[k] - before.get(k, 0) for k in after}


_COUNTERS = ResidencyCounters()
_hooks_lock = threading.Lock()
_jax_hooks_installed = False


def counters() -> ResidencyCounters:
    """The process ledger; installs the retrace listener on first use."""
    _ensure_jax_hooks()
    return _COUNTERS


def _ensure_jax_hooks() -> None:
    """Register the compile-event listener once (idempotent, lazy so a
    jax-less process never imports it)."""
    global _jax_hooks_installed
    if _jax_hooks_installed:
        return
    with _hooks_lock:
        if _jax_hooks_installed:
            return
        _jax_hooks_installed = True
        try:
            import jax

            def _on_duration(name: str, duration: float, **kw) -> None:
                # one backend_compile per XLA compilation; jit cache
                # hits emit nothing, so this counts exactly the
                # retraces the batch-shape bucketing exists to prevent
                if name.endswith("backend_compile_duration"):
                    _COUNTERS.note_retrace()

            jax.monitoring.register_event_duration_secs_listener(
                _on_duration)
        except Exception:  # noqa: BLE001 -- no jax: counters still work
            pass


# -- the transfer seams -----------------------------------------------------


def _is_device_array(arr) -> bool:
    """True for a jax array (the only thing a D2H can move); numpy
    arrays pass the seams unchanged and uncounted."""
    if isinstance(arr, np.ndarray):
        return False
    mod = type(arr).__module__ or ""
    return mod.startswith("jax") or mod.startswith("jaxlib")


def device_put(arr, *args, **kwargs):
    """Counted H2D seam: ``jax.device_put`` with the bytes charged to
    the ledger.  Falls back to a host copy when no jax backend is
    importable (tier/tooling degrade identically to ``_to_device``)."""
    _ensure_jax_hooks()
    try:
        import jax
    except Exception:  # noqa: BLE001 -- no backend: host residency
        return np.ascontiguousarray(arr)
    out = jax.device_put(arr, *args, **kwargs)
    _COUNTERS.note_h2d(getattr(arr, "nbytes", 0))
    return out


def note_h2d(nbytes: int) -> None:
    """Direct H2D accounting hook for call sites that keep their raw
    ``jax.device_put``/``jnp.asarray`` spelling (kernel-module uploads)."""
    _ensure_jax_hooks()
    _COUNTERS.note_h2d(nbytes)


def device_get(arr) -> np.ndarray:
    """Counted D2H seam: the ONE sanctioned way the storage path pulls
    a device value to host.  Inside an open resident section this is a
    violation (recorded or raised per the verifier mode)."""
    _ensure_jax_hooks()
    if not _is_device_array(arr):
        return np.asarray(arr)
    nbytes = int(getattr(arr, "nbytes", 0) or 0)
    _note_d2h_checked(nbytes, "device_get")
    return np.asarray(arr)


def note_d2h(nbytes: int, what: str = "d2h") -> None:
    """Direct D2H accounting hook (section-checked like the seam)."""
    _ensure_jax_hooks()
    _note_d2h_checked(nbytes, what)


def _note_d2h_checked(nbytes: int, what: str) -> None:
    _COUNTERS.note_d2h(nbytes)
    stack = getattr(_tls, "sections", None)
    if stack:
        verifier, name = stack[-1]
        verifier._on_violation(name, what, nbytes)


# -- the section verifier ---------------------------------------------------


class ResidencyViolation:
    """One observed D2H inside a declared device-resident section."""

    __slots__ = ("section", "what", "nbytes")

    def __init__(self, section: str, what: str, nbytes: int):
        self.section = section
        self.what = what
        self.nbytes = nbytes

    def __repr__(self) -> str:
        return (f"D2H transfer ({self.what}, {self.nbytes} bytes) inside "
                f"device-resident section {self.section!r}")


class ResidencySectionError(AssertionError):
    """Raised (raise mode) when a D2H lands inside a resident section."""


_tls = threading.local()


class ResidencyVerifier:
    """Section registry + the runtime guard modes.

    ``mode``: ``"record"`` -- seam violations are appended to
    :attr:`violations` (the tier-1 conftest hook fails the driving
    test); ``"raise"`` -- seam violations raise at the offending call
    AND the section body runs under
    ``jax.transfer_guard_device_to_host("disallow")``.
    """

    def __init__(self, mode: str = "raise"):
        assert mode in ("record", "raise")
        self.mode = mode
        self.violations: List[ResidencyViolation] = []
        #: section names entered at least once (observability)
        self.sections_entered: Dict[str, int] = {}

    def _on_violation(self, section: str, what: str, nbytes: int) -> None:
        v = ResidencyViolation(section, what, nbytes)
        self.violations.append(v)
        if self.mode == "raise":
            raise ResidencySectionError(repr(v))

    @contextlib.contextmanager
    def section(self, name: str):
        stack = getattr(_tls, "sections", None)
        if stack is None:
            stack = _tls.sections = []
        stack.append((self, name))
        self.sections_entered[name] = self.sections_entered.get(name, 0) + 1
        guard = None
        if self.mode == "raise":
            try:
                import jax

                guard = jax.transfer_guard_device_to_host("disallow")
                guard.__enter__()
            except Exception:  # noqa: BLE001 -- no jax / old jax: the
                guard = None   # seam layer still verifies
        try:
            yield
        finally:
            if guard is not None:
                guard.__exit__(None, None, None)
            stack.pop()

    def status(self) -> dict:
        return {
            "mode": self.mode,
            "sections_entered": dict(self.sections_entered),
            "violations": [repr(v) for v in self.violations],
        }


#: process-global verifier (tier-1 conftest installs it); tests that
#: provoke violations on purpose build private instances instead
_GLOBAL: Optional[ResidencyVerifier] = None


def install(mode: str = "raise") -> ResidencyVerifier:
    """Install the global verifier (idempotent per process)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = ResidencyVerifier(mode)
    return _GLOBAL


def global_verifier() -> Optional[ResidencyVerifier]:
    return _GLOBAL


def violations() -> List[ResidencyViolation]:
    return list(_GLOBAL.violations) if _GLOBAL is not None else []


@contextlib.contextmanager
def resident_section(name: str):
    """The runtime guard paired with a ``# cephlint:
    device-resident-section <name>`` comment region.  A no-op when no
    verifier is installed (production default), so the hot path pays
    one attribute probe when the machinery is off."""
    v = _GLOBAL
    if v is None:
        yield
        return
    with v.section(name):
        yield


def status() -> dict:
    """Admin-socket ``residency status`` payload: the ledger plus the
    verifier state."""
    out: dict = {"counters": _COUNTERS.snapshot()}
    if _GLOBAL is not None:
        out.update(_GLOBAL.status())
    else:
        out.update({"mode": "off", "sections_entered": {},
                    "violations": []})
    return out
