"""Native-extension boundary rules (the ``native`` pack).

The round-20 ``_wire_native`` C codec moved the hot wire loop across the
language boundary, out of reach of every Python-AST rule.  This pack
closes that gap using :mod:`ceph_tpu.analysis.native_model`'s
lightweight C parser:

* ``native-refcount-leak-on-error-path`` -- a new (owned) reference is
  still live when the function takes an error exit (``return NULL`` /
  ``return -1`` / ``return PyErr_NoMemory()``) without a
  ``Py_DECREF``/``Py_XDECREF``/``Py_CLEAR``;
* ``native-gil-released-pyapi`` -- a Python C-API call between
  ``Py_BEGIN_ALLOW_THREADS`` and ``Py_END_ALLOW_THREADS`` (the GIL is
  not held there; touching the interpreter corrupts it);
* ``native-missing-fallback`` -- a typed encode path that rejects a
  value-model miss with anything other than ``FallbackError``.  The
  Python peer catches FallbackError and degrades that one message to
  the generic value codec; any other exception class tears the
  connection instead;
* ``native-schema-drift`` (headline) -- the C encoder/decoder dispatch
  branches, linearized to (op, loop-depth, guarded) field sequences by
  the native model, are diffed op-for-op against rules_wire.py's
  linearization of ``msg/wire.py`` -- the same machinery that powers
  ``wire-schema-symmetry``, now applied ACROSS the language boundary.
  Trailing-optional compat tails (``# cephlint: wire-optional`` on the
  Python side, ``d->pos < d->end`` guards on the C side) are part of
  the contract: dropping the guard on either side is drift even when
  each side stays internally consistent.

Like every cephlint rule these are pure source consumers: the C files
are tokenized and parsed, never compiled or imported, and ``msg/wire.py``
is read and ``ast``-parsed by path (importing it would initialize the
codec and potentially invoke make).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis import native_model
from ceph_tpu.analysis.core import (SEV_ERROR, _RULES, FileContext, Finding,
                                    rule)
from ceph_tpu.analysis import rules_wire

_MSG_KEY_RE = re.compile(r"^_?MSG_[A-Z0-9_]+$")


class NativeFileContext:
    """FileContext counterpart for ``.c``/``.cpp`` sources: no AST, a
    :class:`~ceph_tpu.analysis.native_model.NativeModel` instead."""

    is_native = True

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.model = native_model.NativeModel(path, source)

    def finding(self, rule_obj_or_name, line: int, message: str,
                col: int = 0, severity: Optional[str] = None) -> Finding:
        name = getattr(rule_obj_or_name, "name", rule_obj_or_name)
        sev = severity or _RULES[name].severity
        return Finding(name, self.path, line, col, message, sev)


# ---------------------------------------------------------------------------
# refcount / GIL / fallback rules
# ---------------------------------------------------------------------------


@rule(
    "native-refcount-leak-on-error-path", "native", SEV_ERROR,
    "a new (owned) PyObject reference -- classified new-vs-borrowed from "
    "the CPython API table -- is still live at an error exit (return "
    "NULL / return -1 / return PyErr_NoMemory()) with no Py_DECREF/"
    "Py_XDECREF/Py_CLEAR on that path; under FallbackError-heavy "
    "workloads the error path IS the hot path, and each pass leaks the "
    "object",
)
def check_refcount_leak(ctx: NativeFileContext) -> Iterator[Finding]:
    for fn in ctx.model.functions.values():
        for leak in ctx.model.refcount_leaks(fn):
            yield ctx.finding(
                "native-refcount-leak-on-error-path", leak.exit_line,
                f"{fn.name}(): owned reference {leak.var!r} (created line "
                f"{leak.creation_line}) is still live at this error exit "
                "and never Py_DECREF'd on this path",
            )


@rule(
    "native-gil-released-pyapi", "native", SEV_ERROR,
    "a Python C-API call inside a Py_BEGIN/END_ALLOW_THREADS region: the "
    "GIL is released there, so touching the interpreter (allocation, "
    "refcounting, error state) is a data race on the interpreter state; "
    "only GIL-free calls (PyMem_Raw*, PyBytes_AS_STRING-style macro "
    "reads on already-held buffers) are allowed",
)
def check_gil_released_pyapi(ctx: NativeFileContext) -> Iterator[Finding]:
    for fn in ctx.model.functions.values():
        for v in native_model.gil_violations(fn):
            yield ctx.finding(
                "native-gil-released-pyapi", v.line,
                f"{fn.name}(): {v.call}() is called between "
                "Py_BEGIN_ALLOW_THREADS and Py_END_ALLOW_THREADS -- the "
                "GIL is not held here; re-acquire it (Py_BLOCK_THREADS) "
                "or move the call out of the region",
            )


_PYERR_SETTERS = ("PyErr_SetString", "PyErr_Format", "PyErr_SetObject")
_ENC_FN_RE = re.compile(r"^(?:emit_|enc_|encode_|py_encode_)")


@rule(
    "native-missing-fallback", "native", SEV_ERROR,
    "a typed encode path (emit_*/enc_*/encode_*) rejects a value-model "
    "miss with an exception class other than FallbackError; the Python "
    "caller catches FallbackError and degrades that one message to the "
    "generic value codec, while any other class propagates and tears "
    "the connection -- the per-message degradation contract the native "
    "codec was built around",
)
def check_missing_fallback(ctx: NativeFileContext) -> Iterator[Finding]:
    for fn in ctx.model.functions.values():
        if not _ENC_FN_RE.match(fn.name):
            continue
        toks = fn.body_tokens
        for i, t in enumerate(toks):
            if (
                t.kind == "id"
                and t.value in _PYERR_SETTERS
                and i + 1 < len(toks)
                and toks[i + 1].value == "("
            ):
                args = native_model._call_args(toks, i + 1)
                exc = native_model._single_id(args[0]) if args else None
                if exc is not None and exc != "FallbackError":
                    yield ctx.finding(
                        "native-missing-fallback", t.line,
                        f"{fn.name}(): raises {exc} on an encode miss; "
                        "typed encode paths must raise FallbackError so "
                        "the caller degrades this one message to the "
                        "value codec instead of tearing the connection",
                    )


# ---------------------------------------------------------------------------
# native-schema-drift: C field sequences vs msg/wire.py
# ---------------------------------------------------------------------------

#: flattened field: (op, loop-depth, guarded, source line)
_Flat = Tuple[str, int, bool, int]

_OPAQUE = "<opaque>"


def _wire_py_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "msg", "wire.py",
    )


def _find_helper(ctx: FileContext, side: str, norm_name: str):
    word = "encode" if side == "encode" else "decode"
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if word in node.name and \
                    rules_wire._norm_helper(node.name) == norm_name:
                return node
    return None


def _expand_py(items, ctx: FileContext, side: str, depth: int,
               guarded: bool, stack: Set[str]) -> List[_Flat]:
    """Fully flatten a rules_wire Item list: helper calls ("c" items)
    are spliced in-place with their loop-depth offset and guard OR'd."""
    out: List[_Flat] = []
    for it in items:
        line = getattr(it.node, "lineno", 0)
        g = guarded or it.guarded
        d = depth + it.depth
        if it.kind == "opaque":
            out.append((_OPAQUE, d, g, line))
        elif it.kind == "f":
            out.append((it.name, d, g, line))
        else:  # "c" helper
            helper = _find_helper(ctx, side, it.name)
            if helper is None or helper.name in stack:
                out.append((_OPAQUE, d, g, line))
                continue
            sub = rules_wire._extract(helper, side)
            if sub is None:
                out.append((_OPAQUE, d, g, line))
                continue
            stack.add(helper.name)
            out.extend(_expand_py(sub, ctx, side, d, g, stack))
            stack.discard(helper.name)
    return out


def _py_truncate(items: List[_Flat]) -> Tuple[List[_Flat], bool]:
    for i, it in enumerate(items):
        if it[0] == _OPAQUE:
            return items[:i], True
    return items, False


def _py_msg_keys(tree: ast.Module) -> Set[str]:
    keys: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _MSG_KEY_RE.match(node.targets[0].id):
            keys.add(node.targets[0].id)
    return keys


#: cached {(direction) -> {normalized MSG key -> (flat items, truncated,
#: branch line)}} from msg/wire.py, or None when wire.py is unavailable
_PY_SCHEMA: Optional[Dict[str, Dict[str, Tuple[List[_Flat], bool, int]]]]
_PY_SCHEMA = None
_PY_SCHEMA_LOADED = False


def _py_schema() -> Optional[Dict[str, Dict[str, Tuple[List[_Flat], bool,
                                                       int]]]]:
    global _PY_SCHEMA, _PY_SCHEMA_LOADED
    if _PY_SCHEMA_LOADED:
        return _PY_SCHEMA
    _PY_SCHEMA_LOADED = True
    path = _wire_py_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source)
    except (OSError, SyntaxError):
        return None
    ctx = FileContext("ceph_tpu/msg/wire.py", source, tree)
    enc_branches = rules_wire._encoder_branches(ctx)
    dec_branches = rules_wire._decoder_branches(ctx, _py_msg_keys(tree))
    out: Dict[str, Dict[str, Tuple[List[_Flat], bool, int]]] = {
        "encode": {}, "decode": {},
    }
    for direction, branches in (("encode", enc_branches),
                                ("decode", dec_branches)):
        for key, (items, node) in branches.items():
            flat = _expand_py(items, ctx, direction, 0, False, set())
            seq, truncated = _py_truncate(flat)
            out[direction][key.lstrip("_")] = (
                seq, truncated, getattr(node, "lineno", 0))
    _PY_SCHEMA = out
    return out


def _diff_branch(ctx: NativeFileContext, direction: str, key: str,
                 branch: native_model.SchemaBranch,
                 py_seq: List[_Flat], py_truncated: bool,
                 py_line: int) -> Iterator[Finding]:
    """At most ONE finding per (kind, direction): the first divergence."""
    c_seq = list(branch.items)
    side_c = "writes" if direction == "encode" else "reads"
    limit = min(len(c_seq), len(py_seq))
    for i in range(limit):
        c, p = c_seq[i], py_seq[i]
        if (c.op, c.depth) != (p[0], p[1]):
            yield ctx.finding(
                "native-schema-drift", c.line,
                f"message kind {key} ({direction}): field #{i + 1} "
                f"diverges -- C {side_c} {_describe(c.op, c.depth)} but "
                f"msg/wire.py {side_c} {_describe(p[0], p[1])} (wire.py "
                f"line {p[3]}); one side of the language boundary "
                "reordered or retyped a field and every frame now "
                "mis-parses from that offset",
            )
            return
        if c.guarded != p[2]:
            if p[2]:  # py guarded, C not
                where, other = "msg/wire.py", "the C decoder reads it " \
                    "unconditionally"
            else:
                where, other = "the C decoder", "msg/wire.py reads it " \
                    "unconditionally"
            yield ctx.finding(
                "native-schema-drift", c.line,
                f"message kind {key} ({direction}): field #{i + 1} "
                f"({c.op}) is optional-guarded in {where} (wire.py line "
                f"{p[3]}) but {other}; the trailing-optional compat tail "
                "(# cephlint: wire-optional) is a cross-language "
                "contract -- peers that omit the field break the "
                "unguarded side",
            )
            return
    if branch.truncated or py_truncated:
        return
    if len(c_seq) != len(py_seq):
        if len(c_seq) > len(py_seq):
            extra = c_seq[len(py_seq)]
            yield ctx.finding(
                "native-schema-drift", extra.line,
                f"message kind {key} ({direction}): C has trailing "
                f"{_describe(extra.op, extra.depth)} that msg/wire.py "
                f"(line {py_line}) never {side_c}; unguarded length skew "
                "across the language boundary breaks every mixed-codec "
                "peer pair",
            )
        else:
            extra = py_seq[len(c_seq)]
            yield ctx.finding(
                "native-schema-drift", branch.line,
                f"message kind {key} ({direction}): msg/wire.py has "
                f"trailing {_describe(extra[0], extra[1])} (wire.py line "
                f"{extra[3]}) that the C side never {side_c}; unguarded "
                "length skew across the language boundary breaks every "
                "mixed-codec peer pair",
            )


def _describe(op: str, depth: int) -> str:
    return f"{op} (in loop x{depth})" if depth else op


@rule(
    "native-schema-drift", "native", SEV_ERROR,
    "the C codec's typed encode/decode dispatch branches, linearized to "
    "(op, loop-depth, guarded) field sequences, must agree op-for-op "
    "with rules_wire.py's linearization of msg/wire.py -- including the "
    "trailing-optional compat-tail guards (# cephlint: wire-optional / "
    "d->pos < d->end); a field reordered, retyped, added one-sided or "
    "de-guarded across the language boundary is a lint finding here, "
    "not a corpus-lottery runtime bug (FallbackError only catches "
    "per-value misses, never per-schema drift)",
)
def check_schema_drift(ctx: NativeFileContext) -> Iterator[Finding]:
    c_enc = native_model.encoder_branches(ctx.model)
    c_dec = native_model.decoder_branches(ctx.model)
    if not c_enc and not c_dec:
        return
    schema = _py_schema()
    if schema is None:
        return
    for direction, branches in (("encode", c_enc), ("decode", c_dec)):
        py_side = schema[direction]
        for key in sorted(branches):
            norm = key.lstrip("_")
            if norm not in py_side:
                continue  # kind absent on the Python side: degradation
                # via the value codec, not drift
            py_seq, py_trunc, py_line = py_side[norm]
            yield from _diff_branch(ctx, direction, norm, branches[key],
                                    py_seq, py_trunc, py_line)
