"""Wire-tax profiler hygiene rules.

``profile-stage-unpaired``: a ledger stage opened with the paired-call
form (``profiling.stage_enter(marker)``) on a CFG path that can exit
the function without the matching ``stage_exit``.  A stage left open
keeps absorbing time (the exclusive-accounting stack never pops), so
every later cost center under-reports and the decomposition's coverage
gate reads garbage -- the profiler twin of ``trace-span-unfinished``,
built on the same CFG machinery.  The ``with stage(name):`` form closes
itself and is always clean; the paired form exists only for seams where
the result of the staged call must be awaited OUTSIDE the stage (the
coalescer dispatch), and there every enter must reach an exit on every
path -- try/finally is the idiom.

``wire-hot-path-alloc``: per-frame ``bytes`` concatenation inside a
declared ``# cephlint: wire-hot-section`` region.  The zero-copy wire
discipline (docs/messenger.md) moves payloads as part LISTS precisely
so no per-frame copy happens; one stray ``head + body`` on bytes inside
the per-frame seams re-introduces a copy per frame -- the allocation
class the wire-tax profiler's off-mode pin also guards.  Advisory
(warning): list concatenation, ``b"".join`` and out-of-section code are
clean; the bytes-ness of a name is inferred conservatively from its
assignments inside the same function, so only provable concatenations
fire.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ceph_tpu.analysis import cfg as cfg_mod
from ceph_tpu.analysis.core import (SEV_WARNING, FileContext, Finding,
                                    call_attr, parse_wire_hot_sections,
                                    rule)
from ceph_tpu.analysis.rules_trace import _header_exprs, _leaks

_ENTER = "stage_enter"
_EXIT = "stage_exit"


def _stage_stmts(cfg: "cfg_mod.CFG", attr: str) -> List[ast.stmt]:
    """CFG statements whose own expressions call ``*.{attr}(...)``."""
    out: List[ast.stmt] = []
    for stmt in cfg.stmts:
        for node in _header_exprs(stmt):
            if isinstance(node, ast.Call) and call_attr(node) == attr:
                out.append(stmt)
                break
    return out


@rule(
    "profile-stage-unpaired", "ceph", SEV_WARNING,
    "a profiling stage opened with stage_enter() has a control-flow "
    "path that exits the function without stage_exit(): the stage "
    "keeps absorbing time, every later cost center under-reports, and "
    "the decomposition's coverage gate reads garbage -- close it in a "
    "try/finally, or use the `with stage(name):` form when no await "
    "splits the work",
)
def check_stage_unpaired(ctx: FileContext) -> Iterator[Finding]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_enter = any(
            isinstance(node, ast.Call) and call_attr(node) == _ENTER
            for node in ast.walk(fn)
        )
        if not has_enter:
            continue
        graph = cfg_mod.build(fn)
        enters = _stage_stmts(graph, _ENTER)
        if not enters:
            continue
        closers: Set[ast.stmt] = set(_stage_stmts(graph, _EXIT))
        for stmt in enters:
            if _leaks(graph, stmt, closers - {stmt}):
                yield ctx.finding(
                    "profile-stage-unpaired", stmt,
                    "stage_enter() can reach function exit without "
                    "stage_exit(): the open stage swallows every later "
                    "cost center's time; pair it in a try/finally or "
                    "use `with stage(name):`",
                )


# -- wire-hot-path-alloc -----------------------------------------------------

#: call attrs whose result is (conservatively) bytes
_BYTES_CALL_ATTRS = {"tobytes", "to_bytes"}


def _is_bytes_expr(node: ast.AST, known: Set[str]) -> bool:
    """Provably-bytes expression: a bytes literal, bytes()/…tobytes()
    call, ``b"".join(...)``, or a name whose assignments were bytes."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, bytes)
    if isinstance(node, ast.Name):
        return node.id in known
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Name) and func.id == "bytes":
            return True
        if isinstance(func, ast.Attribute):
            if func.attr in _BYTES_CALL_ATTRS:
                return True
            if func.attr == "join" and _is_bytes_expr(func.value, known):
                return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _is_bytes_expr(node.left, known) or \
            _is_bytes_expr(node.right, known)
    if isinstance(node, ast.Subscript):
        # a slice of a bytes value is bytes (buf[pos:])
        return isinstance(node.slice, ast.Slice) and \
            _is_bytes_expr(node.value, known)
    return False


def _bytes_names(fn: ast.AST) -> Set[str]:
    """Names provably bound to bytes somewhere in ``fn`` (two passes so
    ``a = b"" ; b = a + x`` converges)."""
    known: Set[str] = set()
    for _ in range(2):
        before = len(known)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_bytes_expr(node.value, known):
                known.add(node.targets[0].id)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.op, ast.Add) and \
                    isinstance(node.target, ast.Name) and \
                    _is_bytes_expr(node.value, known):
                known.add(node.target.id)
        if len(known) == before:
            break
    return known


def _section_ranges(ctx: FileContext) -> Tuple[List, List]:
    return parse_wire_hot_sections(ctx.lines)


@rule(
    "wire-hot-path-alloc", "ceph", SEV_WARNING,
    "bytes concatenation inside a declared `cephlint: "
    "wire-hot-section` region: the zero-copy wire path moves payloads "
    "as part lists precisely so no per-frame copy happens -- a stray "
    "`a + b` on bytes here costs an allocation and a memcpy per "
    "frame.  Build a part list (Encoder.parts / blob_parts) or hoist "
    "the join out of the per-frame seam; advisory, so a justified "
    "inline disable is acceptable for provably-amortized compaction",
)
def check_wire_hot_alloc(ctx: FileContext) -> Iterator[Finding]:
    sections, problems = _section_ranges(ctx)
    for line, message in problems:
        yield Finding("wire-hot-path-alloc", ctx.path, line, 0,
                      message, SEV_WARNING)
    if not sections:
        return
    spans = [(s.start, s.end, s.name) for s in sections]

    def _section_of(lineno: int):
        for start, end, name in spans:
            if start < lineno < end:
                return name
        return None

    #: per-function bytes-name cache (names are function-scoped)
    fn_names: Dict[ast.AST, Set[str]] = {}
    parents = ctx.parent_map()

    def _known_for(node: ast.AST) -> Set[str]:
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names = fn_names.get(cur)
                if names is None:
                    names = fn_names[cur] = _bytes_names(cur)
                return names
        names = fn_names.get(ctx.tree)
        if names is None:
            names = fn_names[ctx.tree] = _bytes_names(ctx.tree)
        return names

    seen: Set[int] = set()
    for node in ast.walk(ctx.tree):
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            continue
        name = _section_of(lineno)
        if name is None:
            continue
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            known = _known_for(node)
            if _is_bytes_expr(node.left, known) or \
                    _is_bytes_expr(node.right, known):
                if id(node) in seen:
                    continue
                # a nested Add chain (a + b + c) reports once, at the
                # outermost BinOp the walk reaches first
                for sub in ast.walk(node):
                    if sub is not node:
                        seen.add(id(sub))
                yield ctx.finding(
                    "wire-hot-path-alloc", node,
                    f"bytes concatenation inside wire hot section "
                    f"{name!r}: one allocation + memcpy per frame -- "
                    "carry a part list instead of joining",
                )
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.op, ast.Add):
            known = _known_for(node)
            if (isinstance(node.target, ast.Name)
                    and node.target.id in known) or \
                    _is_bytes_expr(node.value, known):
                yield ctx.finding(
                    "wire-hot-path-alloc", node,
                    f"bytes += inside wire hot section {name!r}: "
                    "quadratic per-frame reallocation -- append to a "
                    "part list and join once outside the seam",
                )
