"""cephlint core: findings, the rule registry, and AST helpers.

A *rule* is a function ``check(ctx: FileContext) -> Iterable[Finding]``
registered with the :func:`rule` decorator; the runner calls every
registered rule on every scanned file.  Rules are pure AST/source
consumers -- they never import or execute the code under analysis, so
the analyzer is safe to run over broken or half-written trees (parse
failures surface as a ``parse-error`` finding instead of crashing the
scan).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic at a source location."""

    rule: str
    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str
    severity: str = SEV_WARNING

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity}: [{self.rule}] {self.message}")


@dataclasses.dataclass
class Rule:
    name: str
    pack: str          # "async" | "jax" | "ceph"
    severity: str
    description: str
    check: Callable[["FileContext"], Iterable[Finding]]


#: name -> Rule; populated by the @rule decorator at import time
_RULES: Dict[str, Rule] = {}


def rule(name: str, pack: str, severity: str, description: str):
    """Register a rule-check function under ``name``."""

    def deco(fn):
        if name in _RULES:
            raise ValueError(f"duplicate rule {name!r}")
        _RULES[name] = Rule(name, pack, severity, description, fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    # import the packs lazily so `import ceph_tpu.analysis.core` alone
    # doesn't force them, but any registry consumer sees every rule
    from ceph_tpu.analysis import rules_async  # noqa: F401
    from ceph_tpu.analysis import rules_config  # noqa: F401
    from ceph_tpu.analysis import rules_interleave  # noqa: F401
    from ceph_tpu.analysis import rules_jax  # noqa: F401
    from ceph_tpu.analysis import rules_native  # noqa: F401
    from ceph_tpu.analysis import rules_osdmap  # noqa: F401
    from ceph_tpu.analysis import rules_perf  # noqa: F401
    from ceph_tpu.analysis import rules_profile  # noqa: F401
    from ceph_tpu.analysis import rules_residency  # noqa: F401
    from ceph_tpu.analysis import rules_trace  # noqa: F401
    from ceph_tpu.analysis import rules_wire  # noqa: F401

    return dict(_RULES)


class FileContext:
    """Everything a rule may look at for one file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    # -- shared helpers ----------------------------------------------------

    def parent_map(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def finding(self, rule_obj_or_name, node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        name = getattr(rule_obj_or_name, "name", rule_obj_or_name)
        sev = severity or _RULES[name].severity
        return Finding(name, self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message, sev)

    def imports_module(self, *names: str) -> bool:
        """True if the file imports any of ``names`` (top-level module
        match: ``jax`` matches ``import jax.numpy`` and
        ``from jax import ...``)."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] in names or \
                            alias.name in names:
                        return True
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module.split(".")[0] in names or \
                        node.module in names:
                    return True
        return False


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``asyncio.create_task``,
    ``loop.create_task``, ``().create_task`` (call results collapse to
    ``()``).  Used to match call targets without type inference."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return "()"
    return "?"


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def call_attr(call: ast.Call) -> str:
    """Last attribute segment of the call target (``create_task`` for
    any of the spellings)."""
    return call_name(call).rsplit(".", 1)[-1]


def enclosing_functions(ctx: FileContext, node: ast.AST) -> List[ast.AST]:
    """Function-def chain from outermost to innermost around ``node``."""
    chain: List[ast.AST] = []
    parents = ctx.parent_map()
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            chain.append(cur)
    chain.reverse()
    return chain


def in_async_context(ctx: FileContext, node: ast.AST) -> bool:
    """True when ``node`` executes on the event loop: its *innermost*
    enclosing function is ``async def`` (a nested sync def runs wherever
    it is called from -- the call site gets flagged, not the body)."""
    chain = enclosing_functions(ctx, node)
    return bool(chain) and isinstance(chain[-1], ast.AsyncFunctionDef)


def decorator_names(fn: ast.AST) -> List[str]:
    out = []
    for dec in getattr(fn, "decorator_list", []):
        if isinstance(dec, ast.Call):
            out.append(dotted_name(dec.func))
            out.extend(dotted_name(a) for a in dec.args)
        else:
            out.append(dotted_name(dec))
    return out


def is_jitted(fn: ast.AST) -> bool:
    """Decorated with jax.jit / jit / functools.partial(jax.jit, ...)."""
    return any("jit" == d.rsplit(".", 1)[-1] or d.endswith(".jit")
               for d in decorator_names(fn))


import re as _re

#: declared yield-free regions, marked by comment pairs of the form
#: ``cephlint: atomic-section <name>`` ... ``cephlint:
#: end-atomic-section`` (each after a ``#``).  The annotation is a
#: contract, enforced twice: statically (rules_interleave flags any
#: task-switch point between the markers) and at runtime
#: (analysis/runtime.py asserts no task ever suspends inside one).
_ATOMIC_BEGIN = _re.compile(
    r"#\s*cephlint:\s*atomic-section\s+([A-Za-z0-9_.\-]+)")
_ATOMIC_END = _re.compile(r"#\s*cephlint:\s*end-atomic-section\b")

#: declared device-resident regions: ``cephlint: device-resident-section
#: <name>`` ... ``cephlint: end-device-resident-section``.  Inside the
#: markers no value may leave the device (no D2H sink -- np.asarray,
#: .tolist(), float()/int(), iteration, device_get).  Enforced twice:
#: statically (rules_residency walks the residency lattice through the
#: region, helpers included) and at runtime (analysis/residency.py wraps
#: the paired ``resident_section(name)`` scope in a
#: jax.transfer_guard_device_to_host("disallow") under tier-1).
_RESIDENT_BEGIN = _re.compile(
    r"#\s*cephlint:\s*device-resident-section\s+([A-Za-z0-9_.\-]+)")
_RESIDENT_END = _re.compile(r"#\s*cephlint:\s*end-device-resident-section\b")

#: declared wire hot sections: ``cephlint: wire-hot-section <name>`` ...
#: ``cephlint: end-wire-hot-section``.  Inside the markers the
#: ``wire-hot-path-alloc`` rule (rules_profile) flags per-frame bytes
#: concatenation -- the allocation class the zero-copy part-list
#: discipline (docs/messenger.md) exists to avoid.  Advisory: the
#: declared regions are the per-frame seams the wire-tax profiler
#: instruments, where one stray ``a + b`` on bytes costs a copy per
#: frame.
_WIREHOT_BEGIN = _re.compile(
    r"#\s*cephlint:\s*wire-hot-section\s+([A-Za-z0-9_.\-]+)")
_WIREHOT_END = _re.compile(r"#\s*cephlint:\s*end-wire-hot-section\b")


@dataclasses.dataclass(frozen=True)
class AtomicSection:
    """One declared yield-free region: the markers sit on ``start`` and
    ``end``; the protected statements are the lines strictly between."""

    name: str
    start: int  # 1-based line of the begin marker
    end: int    # 1-based line of the end marker


def _comment_line_numbers(lines) -> "Optional[set]":
    """1-based line numbers that carry a real ``#`` comment token, so
    marker regexes don't fire on marker text quoted inside string
    literals (e.g. a test embedding a marked source as a fixture).
    Returns None when the file doesn't tokenize -- callers fall back to
    treating every line as eligible."""
    import io
    import tokenize
    src = "\n".join(lines) + "\n"
    out = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                out.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return out


def _parse_marked_sections(lines, begin_re, end_re, what: str,
                           end_spelling: str):
    """Shared marker-pair parser: (sections, problems) where problems
    are (line, message) pairs -- an end without a begin, a begin without
    an end, a begin nested inside an open section."""
    sections: List[AtomicSection] = []
    problems: List[tuple] = []
    open_name: Optional[str] = None
    open_line = 0
    if not any(begin_re.search(ln) or end_re.search(ln) for ln in lines):
        return sections, problems
    comment_lines = _comment_line_numbers(lines)
    for i, line in enumerate(lines, start=1):
        if comment_lines is not None and i not in comment_lines:
            continue
        m = begin_re.search(line)
        if m:
            if open_name is not None:
                problems.append((
                    i, f"{what} {m.group(1)!r} opens inside "
                       f"still-open section {open_name!r} (line "
                       f"{open_line}); sections cannot nest"))
            open_name, open_line = m.group(1), i
            continue
        if end_re.search(line):
            if open_name is None:
                problems.append((
                    i, f"{end_spelling} without a matching "
                       f"{what} begin"))
            else:
                sections.append(AtomicSection(open_name, open_line, i))
                open_name = None
    if open_name is not None:
        problems.append((
            open_line,
            f"{what} {open_name!r} is never closed "
            f"(missing {end_spelling})"))
    return sections, problems


def parse_atomic_sections(lines) -> "Tuple[List[AtomicSection], List[Tuple[int, str]]]":  # noqa: E501
    """(sections, problems) from a file's source lines."""
    return _parse_marked_sections(lines, _ATOMIC_BEGIN, _ATOMIC_END,
                                  "atomic-section", "end-atomic-section")


def parse_resident_sections(lines) -> "Tuple[List[AtomicSection], List[Tuple[int, str]]]":  # noqa: E501
    """(sections, problems) for declared device-resident regions."""
    return _parse_marked_sections(
        lines, _RESIDENT_BEGIN, _RESIDENT_END,
        "device-resident-section", "end-device-resident-section")


def parse_wire_hot_sections(lines) -> "Tuple[List[AtomicSection], List[Tuple[int, str]]]":  # noqa: E501
    """(sections, problems) for declared wire hot sections."""
    return _parse_marked_sections(
        lines, _WIREHOT_BEGIN, _WIREHOT_END,
        "wire-hot-section", "end-wire-hot-section")


def module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (lets rules resolve
    e.g. ``os.environ.get(STATE_ENV)`` through the constant)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out
