"""Async hygiene rules (the PR-1 wedge class and its relatives).

The motivating incident: PR 1 lost a full round to a messenger tick
loop whose ``create_task`` result was dropped -- cancellation raced a
``wait_for`` (bpo-42130), the lone cancel was swallowed, and the
immortal loop wedged the entire tier-1 suite.  Every rule here is a
mechanically-detectable face of that bug class.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from ceph_tpu.analysis.core import (SEV_ERROR, SEV_WARNING, FileContext,
                                    Finding, call_attr, call_name,
                                    in_async_context, rule)

_SPAWN_ATTRS = {"create_task", "ensure_future"}

#: call targets that block the event loop when made from a coroutine
_BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.call": "use `asyncio.create_subprocess_exec` or an executor",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec` or an "
                             "executor",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec` or an "
                               "executor",
    "os.system": "use `asyncio.create_subprocess_shell` or an executor",
    "os.popen": "use `asyncio.create_subprocess_shell` or an executor",
}


@rule(
    "async-orphan-task", "async", SEV_ERROR,
    "create_task/ensure_future result dropped: without a retained "
    "reference the task is garbage-collectable mid-flight, and without a "
    "done-callback its exception (or survival across shutdown) is "
    "invisible -- the PR-1 tick-loop wedge class",
)
def check_orphan_task(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        # a spawn whose value is the whole statement: nothing retained
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                and call_attr(node.value) in _SPAWN_ATTRS:
            yield ctx.finding(
                "async-orphan-task", node,
                f"result of {call_name(node.value)}(...) is dropped; "
                "retain it (e.g. messenger.adopt_task) or attach a "
                "done-callback that logs exceptions",
            )
        # an awaited spawn is pointless but not an orphan; skip


def _scope_defs(ctx: FileContext):
    """Lexical name tables: (scope node -> {fn name: is_async}) for
    module/function scopes, plus {method name: is_async} for methods
    (a name defined as BOTH sync and async method anywhere stays
    ambiguous and is dropped -- no types here)."""
    parents = ctx.parent_map()
    scopes: dict = {}
    methods: dict = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        parent = parents.get(node, ctx.tree)
        is_async = isinstance(node, ast.AsyncFunctionDef)
        if isinstance(parent, ast.ClassDef):
            if node.name in methods and methods[node.name] != is_async:
                methods[node.name] = None  # ambiguous across classes
            else:
                methods.setdefault(node.name, is_async)
        # the scope a def's NAME lives in: its innermost enclosing
        # function, else the module
        scope: ast.AST = ctx.tree
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = cur
                break
        scopes.setdefault(scope, {})[node.name] = is_async
    return scopes, methods


@rule(
    "async-unawaited-coroutine", "async", SEV_ERROR,
    "bare call to a coroutine function defined in this module: the "
    "coroutine object is created and silently discarded, the body never "
    "runs (RuntimeWarning at best)",
)
def check_unawaited_coroutine(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    scopes, methods = _scope_defs(ctx)
    if not scopes and not methods:
        return
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Expr) and
                isinstance(node.value, ast.Call)):
            continue
        func = node.value.func
        is_async = None
        name = None
        if isinstance(func, ast.Name):
            # resolve lexically, innermost scope outward (a nested
            # `async def run` must not taint an outer sync `run`)
            name = func.id
            for scope in reversed(
                    [ctx.tree] + enclosing_functions(ctx, node)):
                if name in scopes.get(scope, {}):
                    is_async = scopes[scope][name]
                    break
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            name = func.attr
            is_async = methods.get(name)
        if is_async:
            yield ctx.finding(
                "async-unawaited-coroutine", node,
                f"coroutine {name}(...) is neither awaited nor spawned; "
                "the call creates a coroutine object and drops it",
            )


@rule(
    "async-blocking-call", "async", SEV_WARNING,
    "blocking call inside `async def` stalls the whole event loop (every "
    "dispatch loop, tick and client op on it)",
)
def check_blocking_call(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not in_async_context(ctx, node):
            continue
        name = call_name(node)
        if name in _BLOCKING_CALLS:
            yield ctx.finding(
                "async-blocking-call", node,
                f"{name}(...) blocks the event loop; "
                f"{_BLOCKING_CALLS[name]}",
            )
        elif name == "open":
            yield ctx.finding(
                "async-blocking-call", node,
                "sync file I/O (`open`) inside `async def` blocks the "
                "event loop; move it to `loop.run_in_executor` (or do it "
                "before entering async context)",
            )


@rule(
    "async-drain-per-item", "async", SEV_WARNING,
    "`await writer.drain()` inside a per-item loop that also writes: "
    "one flush (and its coroutine round) per message is the classic "
    "small-message wire overhead -- batch the writes (writelines) and "
    "drain once per burst, or drain on a byte threshold (flow control), "
    "the round-8 corked-messenger discipline",
)
def check_drain_per_item(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    parents = ctx.parent_map()

    def innermost_loop(node, holder):
        """Nearest enclosing loop of ``node`` within the same function
        (a nested def's body does not run under the outer loop)."""
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return cur
        return None

    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Await) and
                isinstance(node.value, ast.Call) and
                call_attr(node.value) == "drain"):
            continue
        loop = innermost_loop(node, None)
        if loop is None:
            continue
        holder = enclosing_functions(ctx, node)
        # per-ITEM: the same innermost loop body performs a unit
        # `.write(...)` -- a loop that only writelines per burst, or
        # whose writes happen in a nested (inner) loop with the drain
        # outside it, is the per-burst shape and stays clean
        for inner in ast.walk(loop):
            if isinstance(inner, ast.Call) and \
                    call_attr(inner) == "write" and \
                    innermost_loop(inner, None) is loop and \
                    enclosing_functions(ctx, inner) == holder:
                yield ctx.finding(
                    "async-drain-per-item", node,
                    "await drain() and a per-item write share this loop "
                    "body; cork the writes (writer.writelines once per "
                    "burst) and drain per burst or on a byte threshold",
                )
                break
        # one finding per drain site is enough


#: awaited call targets that pace a retry loop (sleep/backoff, event or
#: queue parks, deadline-capped waits) -- any one of them in the loop
#: body means the loop is not a hot blind-retry spin
_PACING_ATTRS = {"sleep", "wait", "wait_for", "get", "gather", "acquire"}


def _names_deadline(node: ast.AST) -> bool:
    """A comparison/name that consults a deadline: any identifier
    containing 'deadline'/'timeout', or a ``.time()`` call (loop clock
    reads exist only to be compared against a budget)."""
    for inner in ast.walk(node):
        if isinstance(inner, ast.Name) and (
            "deadline" in inner.id.lower() or "timeout" in inner.id.lower()
        ):
            return True
        if isinstance(inner, ast.Attribute) and (
            "deadline" in inner.attr.lower() or "timeout" in inner.attr.lower()
        ):
            return True
        if isinstance(inner, ast.Call) and call_attr(inner) == "time":
            return True
    return False


@rule(
    "async-unbounded-retry", "async", SEV_WARNING,
    "`while True` retry loop (an except handler that `continue`s) with "
    "no deadline check and no awaited backoff/park in the body: on a "
    "persistent failure it spins the event loop forever and hammers "
    "whatever it is retrying against -- the failure mode the Objecter's "
    "deadline-aware jittered backoff exists to prevent",
)
def check_unbounded_retry(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    parents = ctx.parent_map()

    def innermost_loop(node):
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return cur
        return None

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        test = node.test
        if not (isinstance(test, ast.Constant) and test.value in (True, 1)):
            continue
        if not in_async_context(ctx, node):
            continue
        holder = enclosing_functions(ctx, node)
        # retry signature: an except handler in THIS loop whose body
        # continues the loop (error -> try again)
        retries = False
        for t in ast.walk(node):
            if not isinstance(t, ast.Try) or innermost_loop(t) is not node:
                continue
            for handler in t.handlers:
                for inner in ast.walk(handler):
                    if isinstance(inner, ast.Continue) and \
                            innermost_loop(inner) is node and \
                            enclosing_functions(ctx, inner) == holder:
                        retries = True
        if not retries:
            continue
        # pacing / deadline evidence anywhere in the loop body (same
        # function): an awaited sleep/park, or a deadline consult
        paced = False
        for inner in ast.walk(node):
            if enclosing_functions(ctx, inner) != holder:
                continue
            if isinstance(inner, ast.Await) and \
                    isinstance(inner.value, ast.Call):
                tail = call_attr(inner.value) or \
                    call_name(inner.value).rsplit(".", 1)[-1]
                if tail in _PACING_ATTRS:
                    paced = True
                    break
            if isinstance(inner, (ast.If, ast.Compare)) and \
                    _names_deadline(inner):
                paced = True
                break
        if not paced:
            yield ctx.finding(
                "async-unbounded-retry", node,
                "retry loop without a deadline or backoff: add a "
                "deadline check (fail the op when the budget is spent) "
                "and an awaited, ideally jittered-exponential, delay "
                "between attempts",
            )


#: iterable names that mark a per-client/per-op scale collection --
#: the million-client rule (substring match on the last dotted part):
#: fanning one coroutine/task per element of one of these without a
#: budget admit is exactly how a scale harness OOMs itself
_FANOUT_COLLECTION_MARKS = (
    "client", "conn", "session", "objecter", "peer", "request",
    "waiter", "op_list", "ops", "oids",
)
#: budget evidence: an awaited acquire/admit (semaphore, throttle,
#: QoS admission) or a Semaphore/budget construction in the function
_FANOUT_BUDGET_ATTRS = {"acquire", "admit", "slot", "get"}


def _fanout_collection_name(node: ast.expr) -> Optional[str]:
    """The iterated collection's name when it looks like an unbounded
    client/op set (``self.clients``, ``conns``, ...); None for
    literals, ``range(...)`` worker pools and unmarked names."""
    from ceph_tpu.analysis.core import dotted_name

    if isinstance(node, ast.Call):
        return None  # range(n)/sorted(...) worker-pool shapes
    name = dotted_name(node).rsplit(".", 1)[-1].lower()
    if not name:
        return None
    for mark in _FANOUT_COLLECTION_MARKS:
        if mark in name:
            return name
    return None


def _has_budget_evidence(fn: ast.AST, ctx: FileContext, holder) -> bool:
    """An awaited acquire/admit/slot, a Semaphore construction, or a
    budget-named attribute in ``fn`` (same function scope)."""
    from ceph_tpu.analysis.core import dotted_name, enclosing_functions

    for inner in ast.walk(fn):
        if isinstance(inner, ast.Call):
            tail = dotted_name(inner.func).rsplit(".", 1)[-1]
            if tail in ("Semaphore", "BoundedSemaphore", "Throttle"):
                return True
        if isinstance(inner, ast.Await) and \
                isinstance(inner.value, ast.Call) and \
                enclosing_functions(ctx, inner) == holder:
            attr = call_attr(inner.value)
            if attr in _FANOUT_BUDGET_ATTRS:
                return True
            tgt = dotted_name(inner.value.func).lower()
            if "budget" in tgt or "throttle" in tgt or "admit" in tgt:
                return True
        if isinstance(inner, (ast.Attribute, ast.Name)):
            nm = dotted_name(inner).rsplit(".", 1)[-1].lower()
            if "budget" in nm or "_sem" in nm or nm.endswith("sem"):
                return True
    return False


@rule(
    "async-unbounded-fanout", "async", SEV_WARNING,
    "gather/spawn fan-out over an unbounded client/op collection with "
    "no semaphore/budget admit in scope: at a thousand clients the "
    "coroutine set IS the memory bound, and at a million it is an OOM "
    "-- acquire a budget permit per element (the loadgen "
    "per-client in-flight budget discipline) or bound the pool "
    "(fixed worker count over a queue)",
)
def check_unbounded_fanout(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        budget_known: Optional[bool] = None
        for node in ast.walk(fn):
            holder = enclosing_functions(ctx, node)
            if not holder or holder[-1] is not fn:
                continue
            site = None
            coll = None
            # shape 1: gather(*(f(x) for x in CLIENTS)) / gather(*[...])
            if isinstance(node, ast.Call) and (
                    call_attr(node) == "gather" or
                    call_name(node) == "gather"):
                for arg in node.args:
                    gen = None
                    if isinstance(arg, ast.Starred):
                        gen = arg.value
                    if isinstance(gen, (ast.GeneratorExp, ast.ListComp)):
                        per_item = any(
                            isinstance(x, ast.Call)
                            for x in ast.walk(gen.elt))
                        if per_item and gen.generators:
                            coll = _fanout_collection_name(
                                gen.generators[0].iter)
                            site = node
            # shape 2: for x in CLIENTS: ... create_task(f(x)) ...
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                cname = _fanout_collection_name(node.iter)
                if cname is not None:
                    for inner in ast.walk(node):
                        if isinstance(inner, ast.Call) and \
                                call_attr(inner) in _SPAWN_ATTRS and \
                                enclosing_functions(ctx, inner) == holder:
                            coll = cname
                            site = inner
                            break
            if site is None or coll is None:
                continue
            if budget_known is None:
                budget_known = _has_budget_evidence(fn, ctx, holder)
            if budget_known:
                continue
            yield ctx.finding(
                "async-unbounded-fanout", site,
                f"per-item fan-out over {coll!r} in {fn.name}() with no "
                "semaphore/budget admit in scope; bound it (budget "
                "permit per element, or a fixed worker pool over a "
                "queue)",
            )


def _mentions_lock(node: ast.expr) -> bool:
    """Context-manager expression names a lock: `lock`, `self._lock`,
    `self._conn_lock(node)` ...  The lockdep convention (utils/lockdep)
    is that every lock object's name ends in 'lock'."""
    from ceph_tpu.analysis.core import dotted_name

    if isinstance(node, ast.Call):
        return _mentions_lock(node.func)
    tail = dotted_name(node).rsplit(".", 1)[-1].lower()
    return tail.endswith("lock")


@rule(
    "async-sync-lock-await", "async", SEV_ERROR,
    "await while holding a NON-async lock (`with ...lock:` instead of "
    "`async with`): the awaiting task parks on the loop with the lock "
    "held and every other task that touches it deadlocks -- asyncio "
    "locks (utils/lockdep TrackedLock) are the rail here",
)
def check_sync_lock_await(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):  # async with is fine
            continue
        if not any(_mentions_lock(item.context_expr) for item in node.items):
            continue
        holder = enclosing_functions(ctx, node)
        for inner in ast.walk(node):
            # an await inside a NESTED def does not run under this lock
            if isinstance(inner, ast.Await) and \
                    enclosing_functions(ctx, inner) == holder:
                yield ctx.finding(
                    "async-sync-lock-await", inner,
                    "await inside a sync `with ...lock:` block; hold an "
                    "asyncio lock (`async with`) across await points",
                )
                break  # one finding per with-block is enough


#: await targets that move background data (recovery pushes, scrub /
#: gather reads, fan-out commits) -- exact attr-name match
_BG_IO_ATTRS = {
    "send_message", "send_messages", "_fanout_commit", "_read_shards",
    "_gather_consistent", "batched_sub_reads", "batched_pushes",
}
#: awaited attrs that count as admission/pacing between batches
#: (substring match: throttle.admit, _recovery_pace, asyncio.sleep,
#: wait/wait_for parks, semaphore.acquire)
_BG_PACING_MARKS = ("admit", "pace", "sleep", "throttle", "wait",
                    "acquire")
#: function names that mark background-class work
_BG_NAME_MARKS = ("recover", "scrub", "backfill", "background")


@rule(
    "async-background-unthrottled", "async", SEV_WARNING,
    "background-class loop (recovery/backfill/scrub) issues pushes or "
    "gather reads with no opqueue admit and no awaited pacing between "
    "batches: a rebuild storm then competes unboundedly with client "
    "traffic and starves client p99 -- admit through the "
    "BackgroundThrottle (osd/recovery.py) or await pacing "
    "(osd_recovery_sleep) once per batch",
)
def check_background_unthrottled(ctx: FileContext) -> Iterator[Finding]:
    from ceph_tpu.analysis.core import enclosing_functions

    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        lname = fn.name.lower()
        if not any(mark in lname for mark in _BG_NAME_MARKS):
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            holder = enclosing_functions(ctx, loop)
            if not holder or holder[-1] is not fn:
                continue  # a nested def's loop is its own scope
            first_io = None
            paced = False
            for inner in ast.walk(loop):
                # code inside a nested def does not run under this loop
                if enclosing_functions(ctx, inner) != holder:
                    continue
                if isinstance(inner, ast.Await) and \
                        isinstance(inner.value, ast.Call):
                    attr = call_attr(inner.value)
                    if attr in _BG_IO_ATTRS and first_io is None:
                        first_io = inner
                    elif any(m in attr.lower() for m in _BG_PACING_MARKS):
                        paced = True
                elif isinstance(inner, ast.Call) and \
                        call_attr(inner) == "enqueue":
                    paced = True  # admitted through an op queue
            if first_io is not None and not paced:
                yield ctx.finding(
                    "async-background-unthrottled", first_io,
                    f"loop in background function {fn.name}() awaits "
                    f"{call_attr(first_io.value)}(...) with no throttle "
                    "admit or awaited pacing in the loop body",
                )
