"""Module-level call graph with per-function await summaries.

The single-function rules in ``rules_async.py`` cannot see the bug
class that cost PRs 2, 3 and 5 real rounds: shared-state invariants
broken by a task switch that happens inside a CALLEE.  This module
gives the flow rules the two facts they need:

* **yields** -- a function body contains an await that can actually
  suspend the task (park it on the event loop): an ``await`` of
  anything unresolved (``asyncio.sleep``, ``writer.drain()``, a bare
  future), an ``async for`` or ``async with``;
* **may-await** -- the transitive closure of *yields* over awaited
  calls to functions defined in the same module (plain names resolved
  lexically, ``self.``/``cls.`` methods resolved through the enclosing
  class, then module-wide when unambiguous).

The distinction matters in both directions.  ``await self._helper()``
where ``_helper`` transitively sleeps IS a task-switch point even
though the await's target looks local (the interprocedural positive);
``await self._pure()`` where ``_pure`` is an ``async def`` with no
awaits runs to completion synchronously and can NOT interleave with
another task (the precision negative -- flagging it would teach people
to ignore the rule).  Sync functions never have may-await: only an
``await`` expression yields, and sync bodies cannot contain one.

Like every cephlint component this is a pure AST consumer: nothing
under analysis is imported or executed.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import FileContext, dotted_name

#: caps the fixpoint in pathological trees (cycles converge anyway;
#: this is a pure safety bound)
_MAX_ROUNDS = 50


class FunctionInfo:
    """Summary of one function/method definition."""

    __slots__ = ("qualname", "node", "is_async", "class_name",
                 "direct_yield", "awaited_callees", "may_await")

    def __init__(self, qualname: str, node: ast.AST, is_async: bool,
                 class_name: Optional[str]):
        self.qualname = qualname
        self.node = node
        self.is_async = is_async
        self.class_name = class_name
        #: body awaits something this module cannot prove non-yielding
        self.direct_yield = False
        #: qualnames of module-local functions this body awaits
        self.awaited_callees: Set[str] = set()
        #: fixpoint result: awaiting a call to this function may park
        #: the task on the event loop
        self.may_await = False


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested function
    definitions (a nested def's awaits belong to ITS summary; a nested
    def's body does not run when the outer function does)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Per-module call graph + may-await classification.

    Build one per :class:`FileContext` (rules share it through
    :func:`get`, which memoizes on the context instance).
    """

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        #: qualname -> FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        #: def node -> FunctionInfo (rule-side lookup)
        self.by_node: Dict[ast.AST, FunctionInfo] = {}
        #: lexical name tables: scope node -> {name: qualname}
        self._scopes: Dict[ast.AST, Dict[str, str]] = {}
        #: method name -> qualname when unambiguous module-wide,
        #: else None (two classes define it differently)
        self._methods: Dict[str, Optional[str]] = {}
        #: class name -> {method name -> qualname}
        self._class_methods: Dict[str, Dict[str, str]] = {}
        self._collect()
        self._summarize()
        self._fixpoint()

    # -- construction ------------------------------------------------------

    def _collect(self) -> None:
        parents = self.ctx.parent_map()
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual_parts = [node.name]
            class_name = None
            scope: ast.AST = self.ctx.tree
            cur: ast.AST = node
            while cur in parents:
                cur = parents[cur]
                if isinstance(cur, ast.ClassDef):
                    if class_name is None:
                        class_name = cur.name
                    qual_parts.append(cur.name)
                elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if scope is self.ctx.tree:
                        scope = cur  # the def's NAME lives here
                    qual_parts.append(cur.name)
            qualname = ".".join(reversed(qual_parts))
            info = FunctionInfo(
                qualname, node,
                isinstance(node, ast.AsyncFunctionDef), class_name,
            )
            self.functions[qualname] = info
            self.by_node[node] = info
            self._scopes.setdefault(scope, {})[node.name] = qualname
            if class_name is not None:
                if node.name in self._methods and \
                        self._methods[node.name] != qualname:
                    self._methods[node.name] = None  # ambiguous
                else:
                    self._methods.setdefault(node.name, qualname)
                self._class_methods.setdefault(
                    class_name, {})[node.name] = qualname

    def _resolve_call(self, info: FunctionInfo,
                      call: ast.Call) -> Optional[str]:
        """Qualname of a called module-local function, or None when the
        target is unresolved (external module, computed, ambiguous)."""
        func = call.func
        if isinstance(func, ast.Name):
            # innermost lexical scope outward
            from ceph_tpu.analysis.core import enclosing_functions

            for scope in reversed(
                    [self.ctx.tree] + enclosing_functions(self.ctx, call)):
                table = self._scopes.get(scope)
                if table and func.id in table:
                    return table[func.id]
            return None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            base = func.value.id
            if base in ("self", "cls") and info.class_name is not None:
                own = self._class_methods.get(info.class_name, {})
                if func.attr in own:
                    return own[func.attr]
                return self._methods.get(func.attr)  # None when ambiguous
            if base in self._class_methods:  # ClassName.method(...)
                return self._class_methods[base].get(func.attr)
        return None

    def _summarize(self) -> None:
        for info in self.functions.values():
            if not info.is_async:
                continue  # a sync body cannot contain an await
            for node in _own_nodes(info.node):
                if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                    # the iterator/CM protocol is outside this module:
                    # assume it suspends
                    info.direct_yield = True
                elif isinstance(node, ast.Await):
                    target = node.value
                    callee = self._resolve_call(info, target) \
                        if isinstance(target, ast.Call) else None
                    if callee is None:
                        info.direct_yield = True
                    else:
                        info.awaited_callees.add(callee)

    def _fixpoint(self) -> None:
        for info in self.functions.values():
            info.may_await = info.direct_yield
        for _ in range(_MAX_ROUNDS):
            changed = False
            for info in self.functions.values():
                if info.may_await:
                    continue
                for callee in info.awaited_callees:
                    target = self.functions.get(callee)
                    # awaiting a SYNC local function is a type error at
                    # runtime; treat it as a yield so the site surfaces
                    if target is None or not target.is_async \
                            or target.may_await:
                        info.may_await = True
                        changed = True
                        break
            if not changed:
                break

    # -- queries -----------------------------------------------------------

    def may_await_name(self, qualname: str) -> bool:
        info = self.functions.get(qualname)
        return bool(info and info.may_await)

    def awaiting_functions(self) -> List[str]:
        """Qualnames classified may-await (snapshot/test surface)."""
        return sorted(q for q, i in self.functions.items() if i.may_await)

    def expr_yield_node(self, info: FunctionInfo,
                        expr: ast.AST) -> Optional[ast.AST]:
        """First node inside ``expr`` that can suspend the enclosing
        task, or None.  Nested defs are opaque (their bodies don't run
        here)."""
        for node in self._walk_expr(expr):
            if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                return node
            if isinstance(node, ast.Await):
                target = node.value
                if not isinstance(target, ast.Call):
                    return node
                callee = self._resolve_call(info, target)
                if callee is None:
                    return node
                target_info = self.functions.get(callee)
                if target_info is None or not target_info.is_async or \
                        target_info.may_await:
                    return node
        return None

    @staticmethod
    def _walk_expr(expr: ast.AST) -> Iterator[ast.AST]:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def stmt_yield_node(self, info: FunctionInfo,
                        stmt: ast.stmt) -> Optional[ast.AST]:
        """Like :meth:`expr_yield_node` but for a whole statement,
        without descending into a compound statement's nested block
        statements (those are separate CFG nodes)."""
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                continue
            node = self.expr_yield_node(info, child)
            if node is not None:
                return node
        if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
            return stmt
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[FunctionInfo]:
        parents = self.ctx.parent_map()
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.by_node.get(cur)
        return None


#: FileContext -> CallGraph memo (contexts are per-file, per-scan)
_MEMO: Dict[int, Tuple[FileContext, CallGraph]] = {}


def get(ctx: FileContext) -> CallGraph:
    """The memoized call graph for ``ctx`` (several rules share one
    build per scanned file)."""
    entry = _MEMO.get(id(ctx))
    if entry is not None and entry[0] is ctx:
        return entry[1]
    graph = CallGraph(ctx)
    _MEMO.clear()  # files are scanned one at a time; keep one entry
    _MEMO[id(ctx)] = (ctx, graph)
    return graph
