"""Telemetry-schema rules: perf counters must reach an operator.

``perf-counter-unexported``: a PerfCounters key incremented anywhere in
``ceph_tpu/`` but absent from the telemetry surfaces is invisible in
production -- it exists only for whoever reads the admin socket of the
right daemon at the right moment.  The surfaces are:

* the **report schema** (``ceph_tpu/mgr/report.py``):
  ``REPORTED_COUNTERS`` exact names + ``REPORTED_COUNTER_PREFIXES``
  families -- what ships in MgrReport frames and therefore reaches the
  mgr's aggregated prometheus scrape on the multi-process path;
* the **in-process exposition** (``ceph_tpu/mgr/mgr.py``): counters the
  legacy ClusterState prometheus renderer names explicitly.

Both tables are parsed from the AST (never imported -- the analyzer
must work on a broken tree), mirroring rules_config's OPTIONS
extraction.  Dynamic keys (f-strings, computed names) are skipped; a
counter that is genuinely local gets a justified inline disable.
"""

from __future__ import annotations

import ast
import functools
import os
from typing import Iterator, Optional, Set, Tuple

from ceph_tpu.analysis.core import (SEV_WARNING, FileContext, Finding,
                                    call_attr, call_name,
                                    module_str_constants, rule)

_PERF_METHODS = ("inc", "tinc", "hwm")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@functools.lru_cache(maxsize=1)
def report_schema() -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """(exact names, prefixes) from mgr/report.py's AST."""
    path = os.path.join(_repo_root(), "ceph_tpu", "mgr", "report.py")
    try:
        with open(path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return (), ()
    names: Tuple[str, ...] = ()
    prefixes: Tuple[str, ...] = ()
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        target = node.targets[0].id
        value = node.value
        if isinstance(value, ast.Call) and \
                call_name(value) == "frozenset" and value.args:
            value = value.args[0]
        if not isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            continue
        literals = tuple(
            e.value for e in value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
        if target == "REPORTED_COUNTERS":
            names = literals
        elif target == "REPORTED_COUNTER_PREFIXES":
            prefixes = literals
    return names, prefixes


@functools.lru_cache(maxsize=1)
def exposition_literals() -> Tuple[str, ...]:
    """Every string literal in mgr/mgr.py -- the in-process renderer
    names the counters it exposes explicitly, so membership here counts
    as exported (coarse on purpose: a rename that orphans the renderer
    reference then surfaces as an unexported counter at the inc site)."""
    path = os.path.join(_repo_root(), "ceph_tpu", "mgr", "mgr.py")
    try:
        with open(path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return ()
    return tuple(
        node.value for node in ast.walk(tree)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    )


def _counter_key(call: ast.Call, consts) -> Optional[str]:
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    return None  # dynamic key: out of scope


@rule(
    "perf-counter-unexported", "ceph", SEV_WARNING,
    "perf counter incremented in ceph_tpu/ but absent from the "
    "telemetry surfaces: not in mgr/report.py's REPORTED_COUNTERS / "
    "REPORTED_COUNTER_PREFIXES schema (so it never rides a MgrReport "
    "frame to the mgr's aggregated scrape) and not named by the "
    "in-process prometheus renderer -- operators cannot see it",
)
def check_perf_counter_unexported(ctx: FileContext) -> Iterator[Finding]:
    path = ctx.path.replace("\\", "/")
    if not path.startswith("ceph_tpu/"):
        return  # tools/tests counters are harness-local by design
    if path.endswith(("mgr/report.py", "mgr/mgr.py")):
        return  # the schema/renderer themselves
    names, prefixes = report_schema()
    if not names and not prefixes:
        return  # schema unreadable: stay silent rather than spam
    exported: Set[str] = set(names) | set(exposition_literals())
    consts = module_str_constants(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        attr = call_attr(node)
        if attr not in _PERF_METHODS:
            continue
        segments = call_name(node).split(".")
        if len(segments) < 2 or segments[-2] != "perf":
            continue  # not a PerfCounters surface (e.g. dict.update)
        key = _counter_key(node, consts)
        if key is None:
            continue
        if key in exported or key.startswith(tuple(prefixes)):
            continue
        yield ctx.finding(
            "perf-counter-unexported", node,
            f"counter {key!r} is not in the report schema "
            "(mgr/report.py REPORTED_COUNTERS/_PREFIXES) nor named by "
            "the prometheus renderer; add it to the schema or justify "
            "with an inline disable",
        )
