"""JAX/TPU hygiene rules for the GF(2^8) codec hot paths.

The batching papers this tree follows (arxiv 2108.02692 on XOR-EC
program optimization, arxiv 2112.09017 on TPU-scale linear algebra)
both live or die on two disciplines: no host<->device round-trips
inside the per-stripe loop, and no silent dtype widening of the
GF(2^8) byte domain.  The transfer discipline is now owned by the
flow-aware residency pack (``rules_residency.py``: the old shallow
``jax-host-sync-hot-path`` and ``jax-device-array-iteration`` pattern
checks were retired in its favor -- the lattice knows where a value
lives, so a host array converted in a loop is no longer noise and a
device array leaking through a helper is no longer invisible).  What
stays here:

* dtype rule: array constructors without an explicit ``dtype=`` default
  to float64/int64 -- an 8x widening of a byte lane that XLA will
  happily carry all the way to the MXU; float64 is never right here.
* device-bytes accounting rule: retained device arrays must route
  through the two ledger seams so HBM stays evictable.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ceph_tpu.analysis.core import (SEV_WARNING, FileContext, Finding,
                                    call_name, dotted_name, rule)

#: matrices + ops: everything that builds or consumes GF kernel operands
DTYPE_SCOPE_PREFIXES = ("ceph_tpu/matrices/", "ceph_tpu/ops/")

#: constructors whose dtype defaults to float64/int64
_DEFAULT_DTYPE_CTORS = {
    "np.zeros", "np.ones", "np.empty", "np.arange", "np.eye",
    "numpy.zeros", "numpy.ones", "numpy.empty", "numpy.arange",
    "numpy.eye",
    "jnp.zeros", "jnp.ones", "jnp.empty", "jnp.arange", "jnp.eye",
}


@rule(
    "jax-gf-dtype-drift", "jax", SEV_WARNING,
    "array constructor without an explicit dtype (defaults to "
    "float64/int64) or with float64 in GF kernel scope: the GF(2^8) "
    "byte domain must stay uint8 (wider words are deliberate and "
    "explicit: uint16/uint32 for w=16/32)",
)
def check_dtype_drift(ctx: FileContext) -> Iterator[Finding]:
    if not any(ctx.path.startswith(p) for p in DTYPE_SCOPE_PREFIXES):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in _DEFAULT_DTYPE_CTORS:
            # zeros/ones/empty take dtype as the 2nd positional arg;
            # eye's 2nd positional is M and arange's are stop/step, so
            # those need the keyword
            positional_dtype = (
                name.rsplit(".", 1)[-1] in ("zeros", "ones", "empty")
                and len(node.args) >= 2
            )
            if not positional_dtype and \
                    not any(kw.arg == "dtype" for kw in node.keywords):
                yield ctx.finding(
                    "jax-gf-dtype-drift", node,
                    f"{name}(...) without dtype= defaults to "
                    "float64/int64; GF kernel operands must declare "
                    "their word dtype (uint8 for w<=8)",
                )
        # .astype(np.float64) / dtype=np.float64 anywhere in scope
        if name.rsplit(".", 1)[-1] == "astype" and node.args and \
                dotted_name(node.args[0]).rsplit(".", 1)[-1] == "float64":
            yield ctx.finding(
                "jax-gf-dtype-drift", node,
                ".astype(float64) in GF kernel scope: 8x widening of "
                "the byte lane (float32 is the only sanctioned float "
                "detour, for the MXU dot technique)",
            )
        for kw in node.keywords:
            if kw.arg == "dtype" and \
                    dotted_name(kw.value).rsplit(".", 1)[-1] == "float64":
                yield ctx.finding(
                    "jax-gf-dtype-drift", node,
                    "dtype=float64 in GF kernel scope; use uint8 (or "
                    "the explicit wider word dtype)",
                )


#: the two accounting seams allowed to RETAIN device arrays: every
#: other module must route residency through them so the
#: osd_tier_hbm_bytes ledger (tier/device_tier.py DeviceByteAccount)
#: stays exact and eviction can always reclaim the bytes
DEVICE_BYTES_ACCOUNTING_FILES = (
    "ceph_tpu/tier/device_tier.py",
    "ceph_tpu/ops/pipeline.py",
)

_DEVICE_PUT_CALLS = {
    "jax.device_put", "jax.device_put_sharded", "jax.device_put_replicated",
}


@rule(
    "jax-device-bytes-unaccounted", "jax", SEV_WARNING,
    "device-resident array retention (a jax.device_put result stored on "
    "an attribute or container) outside the tier/pipeline accounting "
    "helpers: HBM held this way is invisible to the osd_tier_hbm_bytes "
    "ledger and can never be evicted under budget pressure -- route it "
    "through DeviceTierStore or the pipeline's H2D cache",
)
def check_device_bytes_unaccounted(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.path.startswith("ceph_tpu/"):
        return  # tools/tests/bench hold device arrays transiently by design
    if ctx.path in DEVICE_BYTES_ACCOUNTING_FILES:
        return
    if not ctx.imports_module("jax"):
        return
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # names bound to device_put results in this function (simple
        # single-function local flow; retention, not transfer, is the
        # concern here, so the full residency lattice is not needed)
        put_names = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    call_name(node.value) in _DEVICE_PUT_CALLS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        put_names.add(tgt.id)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets):
                continue  # plain local bind: transient, fine
            v = node.value
            direct = isinstance(v, ast.Call) and \
                call_name(v) in _DEVICE_PUT_CALLS
            via_name = isinstance(v, ast.Name) and v.id in put_names
            if direct or via_name:
                yield ctx.finding(
                    "jax-device-bytes-unaccounted", node,
                    "device_put result retained on an attribute/container "
                    "outside the accounting seams (tier/device_tier.py, "
                    "ops/pipeline.py): these bytes bypass the "
                    "osd_tier_hbm_bytes ledger",
                )
