"""Lightweight C/C++ model for the native-extension lint pack.

cephlint's Python packs lean on ``ast``; there is no such luxury for the
``.c``/``.cpp`` sources under ``ceph_tpu/native/``.  This module builds
just enough of a model to support the four ``native-*`` rules:

* a tokenizer that strips comments and preprocessor lines (macro bodies
  are deliberately invisible -- a macro call is just an unknown
  function call, which the refcount analysis treats conservatively),
* top-level function extraction (name, parameters, return type),
* a statement-level parser (blocks, if/else, loops, switch/case,
  return/goto/label/break/continue, ``Py_BEGIN/END_ALLOW_THREADS``),
* a refcount dataflow over an explicit CFG, classifying CPython API
  calls as new-vs-borrowed from a table and reporting owned references
  still live at error exits,
* GIL-region facts (which Python C-API calls happen between
  ``Py_BEGIN_ALLOW_THREADS`` and ``Py_END_ALLOW_THREADS``),
* a wire-schema flattener that linearizes each typed ``encode_*`` /
  ``decode_*`` body into the same (op, depth, guarded) item stream
  rules_wire.py derives from ``msg/wire.py`` -- the raw material for
  ``native-schema-drift``.

Everything here must fail SOFT: a function the parser cannot digest
contributes no facts (and no findings) rather than crashing the scan.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Tok:
    kind: str  # "id" | "num" | "str" | "char" | "punct"
    value: str
    line: int


_TWO_CHAR = {
    "->", "++", "--", "==", "!=", "<=", ">=", "&&", "||", "<<", ">>",
    "+=", "-=", "*=", "/=", "%=", "|=", "&=", "^=", "::",
}

_ID_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_ID_CONT = _ID_START | set("0123456789")
_DIGITS = set("0123456789")
_NUM_CONT = _DIGITS | set("abcdefABCDEFxXuUlL.")


def tokenize(source: str) -> List[Tok]:
    """Tokenize C source; comments and preprocessor lines are dropped."""
    toks: List[Tok] = []
    i, n = 0, len(source)
    line = 1
    bol = True  # at beginning of line (modulo whitespace) -> '#' is preproc
    while i < n:
        c = source[i]
        if c == "\n":
            line += 1
            i += 1
            bol = True
            continue
        if c in " \t\r\f\v":
            i += 1
            continue
        if c == "/" and i + 1 < n and source[i + 1] == "/":
            while i < n and source[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and source[i + 1] == "*":
            i += 2
            while i + 1 < n and not (source[i] == "*" and source[i + 1] == "/"):
                if source[i] == "\n":
                    line += 1
                i += 1
            i = min(i + 2, n)
            continue
        if c == "#" and bol:
            # preprocessor directive: skip to end of line, honouring
            # backslash continuations (this hides #define bodies)
            while i < n:
                if source[i] == "\\" and i + 1 < n and source[i + 1] == "\n":
                    line += 1
                    i += 2
                    continue
                if source[i] == "\n":
                    break
                i += 1
            continue
        if c == '"':
            j = i + 1
            while j < n and source[j] != '"':
                if source[j] == "\\":
                    j += 1
                j += 1
            toks.append(Tok("str", source[i + 1 : j], line))
            i = j + 1
            bol = False
            continue
        if c == "'":
            j = i + 1
            while j < n and source[j] != "'":
                if source[j] == "\\":
                    j += 1
                j += 1
            toks.append(Tok("char", source[i + 1 : j], line))
            i = j + 1
            bol = False
            continue
        if c in _ID_START:
            j = i + 1
            while j < n and source[j] in _ID_CONT:
                j += 1
            toks.append(Tok("id", source[i:j], line))
            i = j
            bol = False
            continue
        if c in _DIGITS:
            j = i + 1
            while j < n and source[j] in _NUM_CONT:
                j += 1
            toks.append(Tok("num", source[i:j], line))
            i = j
            bol = False
            continue
        two = source[i : i + 2]
        if two in _TWO_CHAR:
            toks.append(Tok("punct", two, line))
            i += 2
            bol = False
            continue
        toks.append(Tok("punct", c, line))
        i += 1
        bol = False
    return toks


# ---------------------------------------------------------------------------
# statements
# ---------------------------------------------------------------------------


@dataclass
class Stmt:
    kind: str  # Block If Loop Switch Return Goto Label Break Continue Gil Expr
    line: int
    tokens: List[Tok] = field(default_factory=list)  # Expr/Return/Goto/Label
    cond: List[Tok] = field(default_factory=list)  # If/Loop/Switch condition
    body: List["Stmt"] = field(default_factory=list)  # Block/If-then/Loop
    orelse: List["Stmt"] = field(default_factory=list)  # If-else
    cases: List[Tuple[List[List[Tok]], List["Stmt"]]] = field(
        default_factory=list
    )  # Switch: [(case-label-token-runs, stmts)]
    init: List[Tok] = field(default_factory=list)  # for-init
    step: List[Tok] = field(default_factory=list)  # for-step
    marker: str = ""  # Gil: "begin"/"end"; Label/Goto: name; Return macro name


@dataclass
class CFunc:
    name: str
    line: int
    params: List[str]
    pyobject_params: Set[str]
    ret_tokens: List[Tok]
    body: List[Stmt]
    body_tokens: List[Tok]
    parsed: bool

    @property
    def returns_object(self) -> bool:
        ids = {t.value for t in self.ret_tokens if t.kind == "id"}
        return "PyObject" in ids or "PyMODINIT_FUNC" in ids


_KEYWORDS = {
    "if", "else", "while", "for", "do", "switch", "case", "default",
    "return", "break", "continue", "goto", "sizeof", "static", "const",
    "struct", "enum", "union", "typedef", "extern", "inline", "void",
}

_GIL_BEGIN = "Py_BEGIN_ALLOW_THREADS"
_GIL_END = "Py_END_ALLOW_THREADS"
_PY_RETURN_MACROS = {
    "Py_RETURN_NONE", "Py_RETURN_TRUE", "Py_RETURN_FALSE",
    "Py_RETURN_NOTIMPLEMENTED",
}


class _Parser:
    """Statement parser over a token slice (one function body)."""

    def __init__(self, toks: List[Tok]):
        self.toks = toks
        self.i = 0
        self.n = len(toks)

    def peek(self, k: int = 0) -> Optional[Tok]:
        j = self.i + k
        return self.toks[j] if j < self.n else None

    def _run_to(self, closers: str, openers: str) -> List[Tok]:
        """Consume a balanced token run ending just before a top-level
        occurrence of any char in *closers*; tracks () and {} depth."""
        out: List[Tok] = []
        pd = bd = 0
        while self.i < self.n:
            t = self.toks[self.i]
            if t.kind == "punct":
                if pd == 0 and bd == 0 and t.value in closers:
                    return out
                if t.value == "(":
                    pd += 1
                elif t.value == ")":
                    pd -= 1
                elif t.value == "{":
                    bd += 1
                elif t.value == "}":
                    bd -= 1
            out.append(t)
            self.i += 1
        return out

    def _paren_run(self) -> List[Tok]:
        """Consume '( ... )' and return the inner tokens."""
        assert self.toks[self.i].value == "("
        self.i += 1
        out: List[Tok] = []
        depth = 0
        while self.i < self.n:
            t = self.toks[self.i]
            if t.kind == "punct":
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    if depth == 0:
                        self.i += 1
                        return out
                    depth -= 1
            out.append(t)
            self.i += 1
        return out

    def block(self) -> List[Stmt]:
        """Parse '{ ... }' (current token is '{')."""
        assert self.toks[self.i].value == "{"
        self.i += 1
        out: List[Stmt] = []
        while self.i < self.n:
            t = self.toks[self.i]
            if t.kind == "punct" and t.value == "}":
                self.i += 1
                return out
            s = self.stmt()
            if s is not None:
                out.append(s)
        return out

    def stmt(self) -> Optional[Stmt]:
        t = self.peek()
        if t is None:
            return None
        if t.kind == "punct" and t.value == ";":
            self.i += 1
            return None
        if t.kind == "punct" and t.value == "{":
            line = t.line
            return Stmt("Block", line, body=self.block())
        if t.kind == "id":
            v = t.value
            if v == _GIL_BEGIN or v == _GIL_END:
                self.i += 1
                if self.peek() and self.peek().value == ";":
                    self.i += 1
                return Stmt(
                    "Gil", t.line, marker="begin" if v == _GIL_BEGIN else "end"
                )
            if v in _PY_RETURN_MACROS:
                self.i += 1
                if self.peek() and self.peek().value == ";":
                    self.i += 1
                return Stmt("Return", t.line, tokens=[t], marker=v)
            if v == "if":
                self.i += 1
                cond = self._paren_run()
                then = self._sub_stmts()
                orelse: List[Stmt] = []
                nxt = self.peek()
                if nxt and nxt.kind == "id" and nxt.value == "else":
                    self.i += 1
                    orelse = self._sub_stmts()
                return Stmt("If", t.line, cond=cond, body=then, orelse=orelse)
            if v == "while":
                self.i += 1
                cond = self._paren_run()
                body = self._sub_stmts()
                return Stmt("Loop", t.line, cond=cond, body=body)
            if v == "do":
                self.i += 1
                body = self._sub_stmts()
                nxt = self.peek()
                cond: List[Tok] = []
                if nxt and nxt.kind == "id" and nxt.value == "while":
                    self.i += 1
                    cond = self._paren_run()
                    if self.peek() and self.peek().value == ";":
                        self.i += 1
                return Stmt("Loop", t.line, cond=cond, body=body)
            if v == "for":
                self.i += 1
                inner = self._paren_run()
                init, cond, step = _split_for(inner)
                body = self._sub_stmts()
                return Stmt(
                    "Loop", t.line, cond=cond, body=body, init=init, step=step
                )
            if v == "switch":
                self.i += 1
                cond = self._paren_run()
                cases = self._switch_cases()
                return Stmt("Switch", t.line, cond=cond, cases=cases)
            if v == "return":
                self.i += 1
                toks = self._run_to(";", "")
                if self.peek() and self.peek().value == ";":
                    self.i += 1
                return Stmt("Return", t.line, tokens=toks)
            if v == "break" or v == "continue":
                self.i += 1
                if self.peek() and self.peek().value == ";":
                    self.i += 1
                return Stmt("Break" if v == "break" else "Continue", t.line)
            if v == "goto":
                self.i += 1
                name = ""
                if self.peek() and self.peek().kind == "id":
                    name = self.peek().value
                    self.i += 1
                if self.peek() and self.peek().value == ";":
                    self.i += 1
                return Stmt("Goto", t.line, marker=name)
            nxt = self.peek(1)
            if (
                nxt is not None
                and nxt.kind == "punct"
                and nxt.value == ":"
                and v not in _KEYWORDS
            ):
                # label (case/default handled inside _switch_cases)
                self.i += 2
                return Stmt("Label", t.line, marker=v)
        # plain expression statement
        toks = self._run_to(";", "")
        if self.peek() and self.peek().value == ";":
            self.i += 1
        if not toks:
            return None
        return Stmt("Expr", toks[0].line, tokens=toks)

    def _sub_stmts(self) -> List[Stmt]:
        """A single statement or a block, normalized to a list."""
        t = self.peek()
        if t is not None and t.kind == "punct" and t.value == "{":
            return self.block()
        s = self.stmt()
        return [s] if s is not None else []

    def _switch_cases(self) -> List[Tuple[List[List[Tok]], List[Stmt]]]:
        t = self.peek()
        if t is None or t.value != "{":
            return []
        self.i += 1
        cases: List[Tuple[List[List[Tok]], List[Stmt]]] = []
        labels: List[List[Tok]] = []
        stmts: List[Stmt] = []

        def flush():
            nonlocal labels, stmts
            if labels:
                cases.append((labels, stmts))
            labels, stmts = [], []

        while self.i < self.n:
            t = self.peek()
            if t is None:
                break
            if t.kind == "punct" and t.value == "}":
                self.i += 1
                break
            if t.kind == "id" and t.value in ("case", "default"):
                if stmts:
                    flush()
                self.i += 1
                lab = self._run_to(":", "") if t.value == "case" else []
                if self.peek() and self.peek().value == ":":
                    self.i += 1
                labels.append(lab)
                continue
            s = self.stmt()
            if s is not None:
                stmts.append(s)
        flush()
        return cases


def _split_for(inner: List[Tok]) -> Tuple[List[Tok], List[Tok], List[Tok]]:
    """Split for(init; cond; step) inner tokens at top-level ';'."""
    parts: List[List[Tok]] = [[]]
    depth = 0
    for t in inner:
        if t.kind == "punct":
            if t.value in "([{":
                depth += 1
            elif t.value in ")]}":
                depth -= 1
            elif t.value == ";" and depth == 0:
                parts.append([])
                continue
        parts[-1].append(t)
    while len(parts) < 3:
        parts.append([])
    return parts[0], parts[1], parts[2]


# ---------------------------------------------------------------------------
# function extraction
# ---------------------------------------------------------------------------


def extract_functions(toks: List[Tok]) -> List[CFunc]:
    """Find top-level function definitions: ``ID ( params ) {``."""
    funcs: List[CFunc] = []
    depth = 0
    i = 0
    n = len(toks)
    while i < n:
        t = toks[i]
        if t.kind == "punct" and t.value == "{":
            # skip depth bump for extern "C" { / namespace [id] {
            is_linkage = False
            if i >= 2 and toks[i - 1].kind == "str" and toks[i - 2].value == "extern":
                is_linkage = True
            if i >= 1 and toks[i - 1].kind == "id" and toks[i - 1].value == "namespace":
                is_linkage = True
            if (
                i >= 2
                and toks[i - 2].kind == "id"
                and toks[i - 2].value == "namespace"
                and toks[i - 1].kind == "id"
            ):
                is_linkage = True
            if is_linkage:
                i += 1
                continue
            if depth == 0 and i >= 1 and toks[i - 1].value == ")":
                fn = _try_extract_function(toks, i)
                if fn is not None:
                    funcs.append(fn)
                    # skip past the body we just captured
                    i += 1
                    d = 1
                    while i < n and d > 0:
                        if toks[i].kind == "punct":
                            if toks[i].value == "{":
                                d += 1
                            elif toks[i].value == "}":
                                d -= 1
                        i += 1
                    continue
            depth += 1
            i += 1
            continue
        if t.kind == "punct" and t.value == "}":
            depth = max(0, depth - 1)
            i += 1
            continue
        i += 1
    return funcs


def _try_extract_function(toks: List[Tok], brace_i: int) -> Optional[CFunc]:
    # match ')' at brace_i-1 back to its '('
    j = brace_i - 1
    depth = 0
    while j >= 0:
        t = toks[j]
        if t.kind == "punct":
            if t.value == ")":
                depth += 1
            elif t.value == "(":
                depth -= 1
                if depth == 0:
                    break
        j -= 1
    if j <= 0:
        return None
    open_i = j
    name_t = toks[open_i - 1]
    if name_t.kind != "id" or name_t.value in _KEYWORDS:
        return None
    # return-type tokens: from previous ';' or '}' up to the name
    k = open_i - 2
    ret_start = 0
    while k >= 0:
        t = toks[k]
        if t.kind == "punct" and t.value in (";", "}"):
            ret_start = k + 1
            break
        k -= 1
    ret_tokens = toks[ret_start : open_i - 1]
    if not ret_tokens:
        return None  # `foo() {` with no return type isn't a definition here
    params_toks = toks[open_i + 1 : brace_i - 1]
    params, pyobj = _parse_params(params_toks)
    # capture body tokens
    i = brace_i + 1
    d = 1
    body_start = i
    n = len(toks)
    while i < n and d > 0:
        if toks[i].kind == "punct":
            if toks[i].value == "{":
                d += 1
            elif toks[i].value == "}":
                d -= 1
        i += 1
    body_tokens = toks[body_start : i - 1]
    body: List[Stmt] = []
    parsed = True
    try:
        body = _Parser(body_tokens).block_free()
    except Exception:
        parsed = False
        body = []
    return CFunc(
        name=name_t.value,
        line=name_t.line,
        params=params,
        pyobject_params=pyobj,
        ret_tokens=ret_tokens,
        body=body,
        body_tokens=body_tokens,
        parsed=parsed,
    )


def _parser_block_free(self: _Parser) -> List[Stmt]:
    out: List[Stmt] = []
    while self.i < self.n:
        s = self.stmt()
        if s is not None:
            out.append(s)
    return out


_Parser.block_free = _parser_block_free  # type: ignore[attr-defined]


def _parse_params(params_toks: List[Tok]) -> Tuple[List[str], Set[str]]:
    parts: List[List[Tok]] = [[]]
    depth = 0
    for t in params_toks:
        if t.kind == "punct":
            if t.value in "([{":
                depth += 1
            elif t.value in ")]}":
                depth -= 1
            elif t.value == "," and depth == 0:
                parts.append([])
                continue
        parts[-1].append(t)
    names: List[str] = []
    pyobj: Set[str] = set()
    for part in parts:
        ids = [t for t in part if t.kind == "id"]
        if not ids:
            continue
        # name = last id, skipping array-bracket contents
        name = None
        skip = 0
        for t in reversed(part):
            if t.kind == "punct" and t.value == "]":
                skip += 1
            elif t.kind == "punct" and t.value == "[":
                skip -= 1
            elif t.kind == "id" and skip == 0:
                name = t.value
                break
        if name is None or name in ("void",):
            continue
        names.append(name)
        if any(t.value == "PyObject" for t in ids):
            pyobj.add(name)
    return names, pyobj


# ---------------------------------------------------------------------------
# refcount analysis
# ---------------------------------------------------------------------------

# CPython calls returning NEW references
NEW_REF = {
    "PyBytes_FromStringAndSize", "PyUnicode_DecodeUTF8",
    "PyUnicode_InternFromString", "PyUnicode_FromString",
    "PyLong_FromLong", "PyLong_FromLongLong", "PyLong_FromUnsignedLong",
    "PyLong_FromUnsignedLongLong", "PyLong_FromSsize_t",
    "PyLong_FromSize_t", "PyFloat_FromDouble",
    "PyList_New", "PyTuple_New", "PyDict_New",
    "PyObject_GetAttr", "PyObject_GetAttrString",
    "PyObject_Call", "PyObject_CallObject", "PyObject_CallFunction",
    "PyObject_CallMethod", "PyObject_CallNoArgs",
    "PySequence_Fast", "PySequence_GetItem", "PySequence_Tuple",
    "PySequence_List", "PyNumber_Negative", "PyNumber_Index",
    "PyErr_NewException", "PyModule_Create", "PyImport_ImportModule",
    "Py_BuildValue", "PyDict_Copy", "PyObject_Str", "PyObject_Repr",
}

# CPython calls returning BORROWED references
BORROWED_REF = {
    "PyList_GET_ITEM", "PyTuple_GET_ITEM", "PySequence_Fast_GET_ITEM",
    "PyDict_GetItem", "PyDict_GetItemString", "PyList_GetItem",
    "PyTuple_GetItem",
}

# calls that STEAL a reference at the given 1-based argument positions
STEALS = {
    "PyList_SET_ITEM": (3,),
    "PyTuple_SET_ITEM": (3,),
    "PyList_SetItem": (3,),
    "PyTuple_SetItem": (3,),
    "PyModule_AddObject": (3,),
    "Py_XSETREF": (2,),
    "Py_SETREF": (2,),
}

# calls with NO refcount effect on their object arguments (and any
# identifier with these prefixes/suffixes) -- keeps tracking precise
KNOWN_SAFE = {
    "PyBuffer_Release", "PyErr_SetString", "PyErr_Format", "PyErr_Clear",
    "PyErr_Occurred", "PyErr_SetObject", "PyErr_ExceptionMatches",
    "PyList_Append", "PyDict_SetItem", "PyDict_SetItemString",
    "PyObject_SetAttr", "PyObject_SetAttrString", "PyDict_Next",
    "PySequence_Size", "PyObject_Length", "PyObject_Size",
    "PyObject_IsInstance", "PyObject_IsTrue", "PyObject_RichCompareBool",
    "PyLong_AsLong", "PyLong_AsLongLong", "PyLong_AsUnsignedLong",
    "PyLong_AsUnsignedLongLong", "PyLong_AsSsize_t",
    "PyLong_AsUnsignedLongLongMask", "PyFloat_AsDouble",
    "PyUnicode_AsUTF8AndSize", "PyUnicode_AsUTF8",
    "PyObject_GetBuffer", "PyObject_CheckBuffer",
    "PyBytes_GET_SIZE", "PyBytes_AS_STRING", "PyBytes_AsString",
    "PyByteArray_GET_SIZE", "PyByteArray_AS_STRING", "PyByteArray_Size",
    "PySequence_Fast_GET_SIZE", "PySequence_Fast_ITEMS",
    "PyList_GET_SIZE", "PyTuple_GET_SIZE",
    "PyList_Size", "PyTuple_Size", "PyDict_Size",
    "Py_EnterRecursiveCall", "Py_LeaveRecursiveCall", "PyType_Ready",
    "PyErr_NoMemory", "PyErr_WarnEx",
    "memcpy", "memset", "memmove", "strcmp", "strlen", "free", "malloc",
    "realloc",
}

_SAFE_PREFIXES = ("PyMem_",)
_SAFE_SUFFIXES = ("_Check", "_CheckExact")

# refcount-state lattice
UNINIT = "uninit"
NULLVAL = "null"
BORROWED = "borrowed"
OWNED = "owned"
OWNED_MAYBENULL = "owned?"
UNOWNED = "unowned"
UNTRACKED = "untracked"

_OWNEDISH = (OWNED, OWNED_MAYBENULL)


@dataclass(frozen=True)
class RefLeak:
    var: str
    creation_line: int
    exit_line: int


@dataclass(frozen=True)
class GilViolation:
    call: str
    line: int


# GIL-safe identifiers that may appear inside an ALLOW_THREADS region
_GIL_SAFE_EXACT = {
    "PyBytes_AS_STRING", "PyBytes_GET_SIZE", "PyByteArray_AS_STRING",
    "PyByteArray_GET_SIZE", "PyEval_SaveThread", "PyEval_RestoreThread",
    "Py_BEGIN_ALLOW_THREADS", "Py_END_ALLOW_THREADS",
    "Py_BLOCK_THREADS", "Py_UNBLOCK_THREADS",
}
_GIL_SAFE_PREFIXES = ("PyMem_Raw",)


def gil_violations(fn: CFunc) -> List[GilViolation]:
    """Python C-API calls between BEGIN/END_ALLOW_THREADS markers."""
    out: List[GilViolation] = []
    toks = fn.body_tokens
    inside = False
    for i, t in enumerate(toks):
        if t.kind != "id":
            continue
        if t.value == _GIL_BEGIN:
            inside = True
            continue
        if t.value == _GIL_END:
            inside = False
            continue
        if not inside:
            continue
        if not (t.value.startswith("Py") or t.value.startswith("_Py")):
            continue
        nxt = toks[i + 1] if i + 1 < len(toks) else None
        if nxt is None or nxt.value != "(":
            continue
        if t.value in _GIL_SAFE_EXACT:
            continue
        if any(t.value.startswith(p) for p in _GIL_SAFE_PREFIXES):
            continue
        out.append(GilViolation(call=t.value, line=t.line))
    return out


# --- refcount CFG ---------------------------------------------------------


class _RC:
    """Refcount dataflow over one function."""

    def __init__(self, fn: CFunc, model: "NativeModel"):
        self.fn = fn
        self.model = model
        self.leaks: List[RefLeak] = []
        self._seen: Set[Tuple[str, int, int]] = set()
        # tracked variable universe: PyObject* locals and params
        self.tracked: Set[str] = set(fn.pyobject_params)
        self._collect_decls(fn.body)

    def _collect_decls(self, stmts: Sequence[Stmt]) -> None:
        for s in stmts:
            if s.kind == "Expr":
                self._decls_in_tokens(s.tokens)
            elif s.kind == "Loop":
                self._decls_in_tokens(s.init)
                self._collect_decls(s.body)
            elif s.kind in ("Block",):
                self._collect_decls(s.body)
            elif s.kind == "If":
                self._collect_decls(s.body)
                self._collect_decls(s.orelse)
            elif s.kind == "Switch":
                for _labs, body in s.cases:
                    self._collect_decls(body)

    def _decls_in_tokens(self, toks: List[Tok]) -> None:
        # `PyObject * name [= ...][, * name2 [= ...]]*`
        if not toks or toks[0].kind != "id" or toks[0].value != "PyObject":
            return
        i = 1
        depth = 0
        expect_name = True
        while i < len(toks):
            t = toks[i]
            if t.kind == "punct":
                if t.value in "([{":
                    depth += 1
                elif t.value in ")]}":
                    depth -= 1
                elif t.value == "," and depth == 0:
                    expect_name = True
                elif t.value == "=" and depth == 0:
                    expect_name = False
            elif t.kind == "id" and expect_name and depth == 0:
                self.tracked.add(t.value)
                expect_name = False
            i += 1

    # -- state ops --

    def _initial(self) -> Dict[str, Tuple[str, int]]:
        st: Dict[str, Tuple[str, int]] = {}
        for v in self.tracked:
            if v in self.fn.pyobject_params:
                st[v] = (BORROWED, self.fn.line)
            else:
                st[v] = (UNINIT, self.fn.line)
        return st

    @staticmethod
    def _join(
        a: Dict[str, Tuple[str, int]], b: Dict[str, Tuple[str, int]]
    ) -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        for v in set(a) | set(b):
            sa = a.get(v, (UNINIT, 0))
            sb = b.get(v, (UNINIT, 0))
            if sa == sb:
                out[v] = sa
                continue
            ta, tb = sa[0], sb[0]
            line = min(x for x in (sa[1], sb[1]) if x) if (sa[1] or sb[1]) else 0
            if ta == UNTRACKED or tb == UNTRACKED:
                out[v] = (UNTRACKED, line)
            elif ta in _OWNEDISH or tb in _OWNEDISH:
                if ta == OWNED and tb == OWNED:
                    out[v] = (OWNED, line)
                else:
                    out[v] = (OWNED_MAYBENULL, line)
            else:
                out[v] = (UNOWNED, line)
        return out

    # -- call-effect helpers --

    def _apply_call_effects(
        self, toks: List[Tok], st: Dict[str, Tuple[str, int]]
    ) -> None:
        """Scan tokens for calls and apply steal/consume/untrack effects
        to tracked arguments.  Assignment handling is separate."""
        i = 0
        n = len(toks)
        while i < n:
            t = toks[i]
            if t.kind == "id" and i + 1 < n and toks[i + 1].value == "(":
                name = t.value
                args = _call_args(toks, i + 1)
                if name in ("Py_INCREF", "Py_XINCREF"):
                    v = _single_id(args[0]) if args else None
                    if v in self.tracked:
                        cur = st.get(v, (UNINIT, t.line))
                        if name == "Py_XINCREF" and cur[0] in (
                            OWNED_MAYBENULL, NULLVAL, UNINIT,
                        ):
                            st[v] = (OWNED_MAYBENULL, t.line)
                        else:
                            st[v] = (OWNED, t.line)
                elif name in ("Py_DECREF", "Py_XDECREF", "Py_CLEAR"):
                    v = _single_id(args[0]) if args else None
                    if v in self.tracked:
                        st[v] = (UNOWNED, t.line)
                elif name in STEALS:
                    for pos in STEALS[name]:
                        if pos - 1 < len(args):
                            v = _single_id(args[pos - 1])
                            if v in self.tracked:
                                st[v] = (UNOWNED, t.line)
                elif name == "Py_BuildValue":
                    self._build_value(args, st, t.line)
                elif name in (
                    "PyArg_ParseTuple", "PyArg_ParseTupleAndKeywords",
                ):
                    for a in args:
                        v = _addr_of_id(a)
                        if v in self.tracked:
                            st[v] = (BORROWED, t.line)
                elif (
                    name in KNOWN_SAFE
                    or name in NEW_REF
                    or name in BORROWED_REF
                    or any(name.startswith(p) for p in _SAFE_PREFIXES)
                    or any(name.endswith(sfx) for sfx in _SAFE_SUFFIXES)
                ):
                    pass  # no effect on argument ownership
                else:
                    callee = self.model.functions.get(name)
                    if callee is not None:
                        consumed = self.model.may_consume(name)
                        for idx, a in enumerate(args):
                            v = _single_id(a)
                            if v in self.tracked and idx < len(callee.params):
                                if callee.params[idx] in consumed:
                                    st[v] = (UNTRACKED, t.line)
                    else:
                        # unknown call/macro: any tracked arg escapes
                        for a in args:
                            v = _single_id(a) or _addr_of_id(a)
                            if v in self.tracked:
                                st[v] = (UNTRACKED, t.line)
                # skip past the whole call
                i = _skip_call(toks, i + 1)
                continue
            i += 1

    def _build_value(
        self,
        args: List[List[Tok]],
        st: Dict[str, Tuple[str, int]],
        line: int,
    ) -> None:
        if not args or not args[0] or args[0][0].kind != "str":
            # unknown format: be conservative, untrack all id args
            for a in args[1:]:
                v = _single_id(a)
                if v in self.tracked:
                    st[v] = (UNTRACKED, line)
            return
        fmt = args[0][0].value
        argi = 1
        for ch in fmt:
            if ch in "([{)]} ,:":
                continue
            if ch == "#":
                argi += 1  # consumes an extra length arg
                continue
            if ch in "ONS":
                if argi < len(args):
                    v = _single_id(args[argi])
                    if ch in ("N", "S") and v in self.tracked:
                        st[v] = (UNOWNED, line)
                argi += 1
                continue
            argi += 1

    def _rhs_state(
        self, rhs: List[Tok], st: Dict[str, Tuple[str, int]], line: int
    ) -> Tuple[str, int]:
        ids = [t for t in rhs if t.kind == "id"]
        if len(rhs) == 1 and rhs[0].kind == "id":
            v = rhs[0].value
            if v == "NULL":
                return (NULLVAL, line)
            if v in ("Py_None", "Py_True", "Py_False", "Py_NotImplemented"):
                return (BORROWED, line)
            if v in self.tracked:
                return st.get(v, (UNTRACKED, line))
            return (BORROWED, line)  # module-level global
        if len(rhs) == 1 and rhs[0].kind == "num":
            return (NULLVAL, line) if rhs[0].value == "0" else (UNTRACKED, line)
        # scan calls in the RHS
        has_new = has_borrowed = False
        i = 0
        while i < len(rhs):
            t = rhs[i]
            if t.kind == "id" and i + 1 < len(rhs) and rhs[i + 1].value == "(":
                name = t.value
                if name in NEW_REF:
                    has_new = True
                elif name in BORROWED_REF:
                    has_borrowed = True
                else:
                    callee = self.model.functions.get(name)
                    if callee is not None and callee.returns_object:
                        has_new = True
            i += 1
        if has_new:
            return (OWNED_MAYBENULL, line)
        if has_borrowed:
            return (BORROWED, line)
        if not ids:
            return (UNTRACKED, line)
        return (UNTRACKED, line)

    # -- error exits --

    @staticmethod
    def _is_error_return(toks: List[Tok], marker: str) -> bool:
        if marker in _PY_RETURN_MACROS:
            return False
        vals = [t.value for t in toks if not (t.kind == "id" and t.value == "return")]
        if vals == ["NULL"]:
            return True
        if vals == ["-", "1"]:
            return True
        if vals and vals[0] == "PyErr_NoMemory":
            return True
        return False

    def _report_exit(
        self, st: Dict[str, Tuple[str, int]], exit_line: int
    ) -> None:
        for v, (tag, cline) in sorted(st.items()):
            if tag in _OWNEDISH:
                key = (v, cline, exit_line)
                if key not in self._seen:
                    self._seen.add(key)
                    self.leaks.append(
                        RefLeak(var=v, creation_line=cline, exit_line=exit_line)
                    )

    # -- condition refinement --

    def _cond_facts(
        self, cond: List[Tok]
    ) -> Tuple[List[Tuple[str, str]], List[Tuple[str, str]]]:
        """Return (true_facts, false_facts): lists of (var, 'null'|'nonnull')
        that hold on the respective branch.  Conservative: only simple
        null-test shapes produce facts."""
        return _cond_facts_rec(cond, self.tracked)

    @staticmethod
    def _refine(
        st: Dict[str, Tuple[str, int]], facts: List[Tuple[str, str]]
    ) -> Dict[str, Tuple[str, int]]:
        if not facts:
            return st
        out = dict(st)
        for v, what in facts:
            cur = out.get(v)
            if cur is None:
                continue
            tag, line = cur
            if what == "null" and tag == OWNED_MAYBENULL:
                out[v] = (NULLVAL, line)
            elif what == "nonnull":
                if tag == OWNED_MAYBENULL:
                    out[v] = (OWNED, line)
                elif tag == NULLVAL:
                    out[v] = (UNOWNED, line)  # dead path
        return out

    # -- interpreter --

    def run(self) -> List[RefLeak]:
        if not self.fn.parsed or not self.tracked:
            return []
        try:
            self._exec_seq(self.fn.body, self._initial(), depth=0)
        except _Bail:
            return []
        except RecursionError:
            return []
        return self.leaks

    def _exec_seq(
        self,
        stmts: Sequence[Stmt],
        st: Dict[str, Tuple[str, int]],
        depth: int,
        labels: Optional[Dict[str, Tuple[Sequence[Stmt], int]]] = None,
    ) -> Optional[Dict[str, Tuple[str, int]]]:
        """Execute statements; returns the fall-through state or None if
        all paths terminated (return/goto).  Branches are explored by
        recursive path enumeration with a depth cap."""
        if depth > 64:
            raise _Bail()
        if labels is None:
            labels = _collect_labels(stmts)
        i = 0
        while i < len(stmts):
            s = stmts[i]
            rest = stmts[i + 1 :]
            if s.kind == "Expr":
                self._exec_expr(s.tokens, st)
                i += 1
                continue
            if s.kind == "Gil":
                i += 1
                continue
            if s.kind == "Label":
                i += 1
                continue
            if s.kind == "Block":
                sub = self._exec_seq(s.body, st, depth + 1, labels)
                if sub is None:
                    return None
                st = sub
                i += 1
                continue
            if s.kind == "Return":
                # apply call effects in the return expression first
                expr = [
                    t
                    for t in s.tokens
                    if not (t.kind == "id" and t.value == "return")
                ]
                self._apply_call_effects(expr, st)
                v = _returned_var(expr)
                if v in self.tracked:
                    st = dict(st)
                    st[v] = (UNOWNED, s.line)
                if self._is_error_return(s.tokens, s.marker):
                    self._report_exit(st, s.line)
                return None
            if s.kind == "Goto":
                target = labels.get(s.marker)
                if target is None:
                    # unknown label: treat as terminating without report
                    return None
                tstmts, ti = target
                self._exec_seq(tstmts[ti:], st, depth + 1, labels)
                return None
            if s.kind in ("Break", "Continue"):
                return dict(st)  # loop bodies are executed once; fall out
            if s.kind == "If":
                self._apply_call_effects(s.cond, st)
                tf, ff = self._cond_facts(s.cond)
                st_t = self._refine(dict(st), tf)
                st_f = self._refine(dict(st), ff)
                out_t = self._exec_seq(
                    list(s.body) + list(rest), st_t, depth + 1, labels
                )
                out_f = self._exec_seq(
                    list(s.orelse) + list(rest), st_f, depth + 1, labels
                )
                if out_t is None and out_f is None:
                    return None
                if out_t is None:
                    return out_f
                if out_f is None:
                    return out_t
                return self._join(out_t, out_f)
            if s.kind == "Loop":
                self._apply_call_effects(s.init, st)
                self._decl_assigns(s.init, st)
                self._apply_call_effects(s.cond, st)
                # run the body once (conservative single unrolling),
                # then join with the skip path
                body_out = self._exec_seq(list(s.body), dict(st), depth + 1, labels)
                self._apply_call_effects(s.step, st)
                if body_out is not None:
                    self._apply_call_effects(s.step, body_out)
                    st = self._join(st, body_out)
                i += 1
                continue
            if s.kind == "Switch":
                self._apply_call_effects(s.cond, st)
                outs: List[Dict[str, Tuple[str, int]]] = []
                any_falls = False
                for _labs, body in s.cases:
                    o = self._exec_seq(list(body), dict(st), depth + 1, labels)
                    if o is not None:
                        outs.append(o)
                        any_falls = True
                if not s.cases:
                    any_falls = True
                    outs.append(dict(st))
                if not any_falls:
                    # no default branch may still fall through
                    has_default = any(
                        any(not lab for lab in labs) for labs, _b in s.cases
                    )
                    if not has_default:
                        outs.append(dict(st))
                if not outs:
                    return None
                acc = outs[0]
                for o in outs[1:]:
                    acc = self._join(acc, o)
                st = acc
                i += 1
                continue
            i += 1
        return st

    def _decl_assigns(
        self, toks: List[Tok], st: Dict[str, Tuple[str, int]]
    ) -> None:
        """Handle assignments inside for-init token runs."""
        self._exec_expr(toks, st)

    def _exec_expr(self, toks: List[Tok], st: Dict[str, Tuple[str, int]]) -> None:
        # declaration with (possibly several) declarators:
        #   PyObject *a = X, *b = Y;
        if toks and toks[0].kind == "id" and toks[0].value == "PyObject":
            for part in _split_top(toks[1:], ","):
                eq = None
                depth = 0
                for i, t in enumerate(part):
                    if t.kind == "punct":
                        if t.value in "([{":
                            depth += 1
                        elif t.value in ")]}":
                            depth -= 1
                        elif t.value == "=" and depth == 0:
                            eq = i
                            break
                if eq is None:
                    continue
                name = None
                for t in part[:eq]:
                    if t.kind == "id":
                        name = t.value
                rhs = part[eq + 1 :]
                self._apply_call_effects(rhs, st)
                if name in self.tracked:
                    st[name] = self._rhs_state(
                        _strip_casts(rhs), st, part[0].line if part else 0
                    )
            return
        # plain assignment: `name = RHS` (single top-level '=')
        eq_i = None
        depth = 0
        for i, t in enumerate(toks):
            if t.kind == "punct":
                if t.value in "([{":
                    depth += 1
                elif t.value in ")]}":
                    depth -= 1
                elif t.value == "=" and depth == 0:
                    eq_i = i
                    break
        if eq_i is not None:
            lhs = toks[:eq_i]
            rhs = toks[eq_i + 1 :]
            target = None
            for t in reversed(lhs):
                if t.kind == "id":
                    target = t.value
                    break
                if t.kind == "punct" and t.value in ("*", "const"):
                    continue
                break
            self._apply_call_effects(rhs, st)
            if target in self.tracked:
                st[target] = self._rhs_state(
                    _strip_casts(rhs), st, toks[0].line
                )
            return
        self._apply_call_effects(toks, st)


class _Bail(Exception):
    pass


def _collect_labels(
    stmts: Sequence[Stmt],
) -> Dict[str, Tuple[Sequence[Stmt], int]]:
    labels: Dict[str, Tuple[Sequence[Stmt], int]] = {}

    def walk(seq: Sequence[Stmt]) -> None:
        for i, s in enumerate(seq):
            if s.kind == "Label":
                labels[s.marker] = (seq, i + 1)
            if s.kind in ("Block", "If", "Loop"):
                walk(s.body)
            if s.kind == "If":
                walk(s.orelse)
            if s.kind == "Switch":
                for _labs, body in s.cases:
                    walk(body)

    walk(stmts)
    return labels


def _call_args(toks: List[Tok], open_i: int) -> List[List[Tok]]:
    """Split the args of the call whose '(' is at open_i."""
    args: List[List[Tok]] = [[]]
    depth = 0
    i = open_i + 1
    while i < len(toks):
        t = toks[i]
        if t.kind == "punct":
            if t.value == "(":
                depth += 1
            elif t.value == ")":
                if depth == 0:
                    break
                depth -= 1
            elif t.value == "," and depth == 0:
                args.append([])
                i += 1
                continue
        args[-1].append(t)
        i += 1
    if args == [[]]:
        return []
    return args


def _skip_call(toks: List[Tok], open_i: int) -> int:
    """Index just past the ')' matching the '(' at open_i."""
    depth = 0
    i = open_i
    while i < len(toks):
        t = toks[i]
        if t.kind == "punct":
            if t.value == "(":
                depth += 1
            elif t.value == ")":
                depth -= 1
                if depth == 0:
                    return i + 1
        i += 1
    return i


def _single_id(toks: List[Tok]) -> Optional[str]:
    toks = _strip_casts(toks)
    if len(toks) == 1 and toks[0].kind == "id":
        return toks[0].value
    return None


def _addr_of_id(toks: List[Tok]) -> Optional[str]:
    if (
        len(toks) == 2
        and toks[0].kind == "punct"
        and toks[0].value == "&"
        and toks[1].kind == "id"
    ):
        return toks[1].value
    return None


def _strip_casts(toks: List[Tok]) -> List[Tok]:
    """Strip a leading `( type... * )` cast."""
    if (
        len(toks) >= 3
        and toks[0].kind == "punct"
        and toks[0].value == "("
    ):
        depth = 0
        for i, t in enumerate(toks):
            if t.kind == "punct":
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    depth -= 1
                    if depth == 0:
                        inner = toks[1:i]
                        rest = toks[i + 1 :]
                        if rest and all(
                            t2.kind == "id" or t2.value in ("*", "const")
                            for t2 in inner
                        ):
                            return _strip_casts(rest)
                        return toks
        return toks
    return toks


def _returned_var(expr: List[Tok]) -> Optional[str]:
    return _single_id(expr)


def _cond_facts_rec(
    cond: List[Tok], tracked: Set[str]
) -> Tuple[List[Tuple[str, str]], List[Tuple[str, str]]]:
    cond = _strip_outer_parens(cond)
    if not cond:
        return [], []
    # split on top-level || first (lowest precedence), then &&
    or_parts = _split_top(cond, "||")
    if len(or_parts) > 1:
        # true-branch: nothing certain; false-branch: all disjuncts false
        false_facts: List[Tuple[str, str]] = []
        for p in or_parts:
            _t, f = _cond_facts_rec(p, tracked)
            false_facts.extend(f)
        return [], false_facts
    and_parts = _split_top(cond, "&&")
    if len(and_parts) > 1:
        true_facts: List[Tuple[str, str]] = []
        for p in and_parts:
            t, _f = _cond_facts_rec(p, tracked)
            true_facts.extend(t)
        return true_facts, []
    # atoms
    vals = [t.value for t in cond]
    if (
        len(cond) == 3
        and cond[1].value == "=="
        and (
            (cond[0].kind == "id" and cond[2].value == "NULL")
            or (cond[2].kind == "id" and cond[0].value == "NULL")
        )
    ):
        v = cond[0].value if cond[2].value == "NULL" else cond[2].value
        if v in tracked:
            return [(v, "null")], [(v, "nonnull")]
        return [], []
    if (
        len(cond) == 3
        and cond[1].value == "!="
        and (
            (cond[0].kind == "id" and cond[2].value == "NULL")
            or (cond[2].kind == "id" and cond[0].value == "NULL")
        )
    ):
        v = cond[0].value if cond[2].value == "NULL" else cond[2].value
        if v in tracked:
            return [(v, "nonnull")], [(v, "null")]
        return [], []
    if len(cond) == 2 and vals[0] == "!" and cond[1].kind == "id":
        v = cond[1].value
        if v in tracked:
            return [(v, "null")], [(v, "nonnull")]
        return [], []
    if len(cond) == 1 and cond[0].kind == "id":
        v = cond[0].value
        if v in tracked:
            return [(v, "nonnull")], [(v, "null")]
    return [], []


def _strip_outer_parens(toks: List[Tok]) -> List[Tok]:
    while (
        len(toks) >= 2
        and toks[0].value == "("
        and toks[-1].value == ")"
    ):
        depth = 0
        balanced = True
        for i, t in enumerate(toks):
            if t.kind == "punct":
                if t.value == "(":
                    depth += 1
                elif t.value == ")":
                    depth -= 1
                    if depth == 0 and i != len(toks) - 1:
                        balanced = False
                        break
        if not balanced:
            return toks
        toks = toks[1:-1]
    return toks


def _split_top(toks: List[Tok], op: str) -> List[List[Tok]]:
    parts: List[List[Tok]] = [[]]
    depth = 0
    for t in toks:
        if t.kind == "punct":
            if t.value in "([{":
                depth += 1
            elif t.value in ")]}":
                depth -= 1
            elif t.value == op and depth == 0:
                parts.append([])
                continue
        parts[-1].append(t)
    return parts


# ---------------------------------------------------------------------------
# wire-schema flattener
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SchemaItem:
    op: str  # u8 u16 u32 u64 varint blob string value
    depth: int
    guarded: bool
    line: int
    arg: Optional[str] = None  # u8 discriminator constant, when a bare ID


_ERRORPATH = object()  # sentinel: this path only error-exits
_OPAQUE_ITEM = SchemaItem(op="<opaque>", depth=0, guarded=False, line=0)

_ENC_PRIM_RE = re.compile(r"^(?:emit|enc)_(u8|u16|u32|u64|varint|blob|string)$")
_DEC_PRIM_RE = re.compile(r"^dec_(u8|u16|u32|u64|varint|blob|string)(?:_obj)?$")
_GUARD_RE = re.compile(r"\w+\s*->\s*pos\s*<\s*\w+\s*->\s*end")
_WT_CONST_RE = re.compile(r"^WT_[A-Z0-9_]+$")
_MSG_CONST_RE = re.compile(r"^_?MSG_[A-Z0-9_]+$")


class _SchemaFlattener:
    def __init__(self, model: "NativeModel", side: str):
        assert side in ("enc", "dec")
        self.model = model
        self.side = side
        self._memo: Dict[str, Optional[List[SchemaItem]]] = {}
        self._stack: Set[str] = set()

    # -- value-codec seeds: atomic `value` ops ----------------------------

    def _is_value_seed(self, fn: CFunc) -> bool:
        toks = fn.body_tokens
        if self.side == "enc":
            # direct emit_u8(_, WT_*|<own param>) call
            for i, t in enumerate(toks):
                if (
                    t.kind == "id"
                    and t.value == "emit_u8"
                    and i + 1 < len(toks)
                    and toks[i + 1].value == "("
                ):
                    args = _call_args(toks, i + 1)
                    if len(args) >= 2:
                        v = _single_id(args[1])
                        if v is not None and (
                            _WT_CONST_RE.match(v) or v in fn.params
                        ):
                            return True
            return False
        # dec side: `case WT_*` labels or WT_* comparisons in the body
        for t in toks:
            if t.kind == "id" and _WT_CONST_RE.match(t.value):
                return True
        return False

    def classify_call(self, name: str) -> Optional[str]:
        """Return an op name for primitive/value calls, 'helper' for
        in-file codec helpers, None for everything else."""
        if self.side == "enc":
            if name in ("emit_value",):
                return "value"
            m = _ENC_PRIM_RE.match(name)
            if m:
                return m.group(1)
            fn = self.model.functions.get(name)
            if fn is not None and name.startswith(("emit_", "enc_", "encode_")):
                if self._is_value_seed(fn):
                    return "value"
                return "helper"
            return None
        if name in ("dec_value",):
            return "value"
        m = _DEC_PRIM_RE.match(name)
        if m:
            return m.group(1)
        fn = self.model.functions.get(name)
        if fn is not None and name.startswith(("dec_", "decode_")):
            if self._is_value_seed(fn):
                return "value"
            return "helper"
        return None

    # -- expression op extraction ----------------------------------------

    def expr_ops(
        self, toks: List[Tok], depth: int, guarded: bool
    ) -> List[SchemaItem]:
        out: List[SchemaItem] = []
        i = 0
        n = len(toks)
        while i < n:
            t = toks[i]
            if t.kind == "id" and i + 1 < n and toks[i + 1].value == "(":
                cls = self.classify_call(t.value)
                if cls is None:
                    i += 1  # descend into args naturally
                    continue
                if cls == "helper":
                    sub = self.flatten_fn(t.value)
                    if sub is None:
                        out.append(_OPAQUE_ITEM)
                    else:
                        for it in sub:
                            out.append(
                                SchemaItem(
                                    op=it.op,
                                    depth=it.depth + depth,
                                    guarded=it.guarded or guarded,
                                    line=t.line,
                                    arg=it.arg,
                                )
                            )
                    i = _skip_call(toks, i + 1)
                    continue
                arg = None
                if cls == "u8":
                    args = _call_args(toks, i + 1)
                    if len(args) >= 2:
                        arg = _single_id(args[1])
                out.append(
                    SchemaItem(
                        op=cls, depth=depth, guarded=guarded, line=t.line, arg=arg
                    )
                )
                i = _skip_call(toks, i + 1)
                continue
            i += 1
        return out


    # -- statement flattening (suffix semantics) -------------------------

    def flatten_fn(self, name: str) -> Optional[List[SchemaItem]]:
        if name in self._memo:
            return self._memo[name]
        if name in self._stack:
            return None  # recursion -> opaque
        fn = self.model.functions.get(name)
        if fn is None or not fn.parsed:
            self._memo[name] = None
            return None
        self._stack.add(name)
        try:
            res = self.flatten_stmts(list(fn.body), 0, False, 0)
        finally:
            self._stack.discard(name)
        if res is _ERRORPATH:
            res = []
        self._memo[name] = res
        return res

    def flatten_stmts(
        self,
        stmts: List[Stmt],
        depth: int,
        guarded: bool,
        rec: int,
    ):
        """Flatten a statement sequence to SchemaItems, or _ERRORPATH if
        every path through it error-exits."""
        if rec > 200:
            return [_OPAQUE_ITEM]
        out: List[SchemaItem] = []
        i = 0
        while i < len(stmts):
            s = stmts[i]
            rest = stmts[i + 1 :]
            if s.kind == "Expr" or s.kind == "Gil":
                out.extend(self.expr_ops(s.tokens, depth, guarded))
                i += 1
                continue
            if s.kind == "Block":
                sub = self.flatten_stmts(
                    list(s.body) + list(rest), depth, guarded, rec + 1
                )
                if sub is _ERRORPATH:
                    return _ERRORPATH
                return out + sub
            if s.kind == "Return":
                if self._is_error_return(s):
                    return _ERRORPATH
                expr = [
                    t
                    for t in s.tokens
                    if not (t.kind == "id" and t.value == "return")
                ]
                out.extend(self.expr_ops(expr, depth, guarded))
                return out
            if s.kind == "Goto":
                return _ERRORPATH  # goto fail idiom
            if s.kind in ("Break", "Continue"):
                return out
            if s.kind == "Label":
                i += 1
                continue
            if s.kind == "If":
                out.extend(self.expr_ops(s.cond, depth, guarded))
                if self._is_guard(s.cond):
                    sub = self.flatten_stmts(list(s.body), depth, True, rec + 1)
                    if sub is _ERRORPATH:
                        out.append(_OPAQUE_ITEM)
                    else:
                        out.extend(sub)
                    if s.orelse:
                        esub = self.flatten_stmts(
                            list(s.orelse), depth, True, rec + 1
                        )
                        if esub is _ERRORPATH or (esub and len(esub) > 0):
                            out.append(_OPAQUE_ITEM)
                    i += 1
                    continue
                t_arm = self.flatten_stmts(
                    list(s.body) + list(rest), depth, guarded, rec + 1
                )
                e_arm = self.flatten_stmts(
                    list(s.orelse) + list(rest), depth, guarded, rec + 1
                )
                if t_arm is _ERRORPATH and e_arm is _ERRORPATH:
                    return _ERRORPATH
                if t_arm is _ERRORPATH:
                    return out + e_arm
                if e_arm is _ERRORPATH:
                    return out + t_arm
                if _items_equal(t_arm, e_arm):
                    return out + t_arm
                return out + [_OPAQUE_ITEM]
            if s.kind == "Loop":
                out.extend(self.expr_ops(s.init, depth, guarded))
                cond_ops = self.expr_ops(s.cond, depth, guarded)
                if cond_ops:
                    # codec ops inside a loop condition: opaque (mirrors
                    # rules_wire's while handling)
                    out.append(_OPAQUE_ITEM)
                    i += 1
                    continue
                sub = self.flatten_stmts(list(s.body), depth + 1, guarded, rec + 1)
                if sub is _ERRORPATH:
                    out.append(_OPAQUE_ITEM)
                else:
                    out.extend(sub)
                out.extend(self.expr_ops(s.step, depth, guarded))
                i += 1
                continue
            if s.kind == "Switch":
                arms = []
                for _labs, body in s.cases:
                    a = self.flatten_stmts(list(body), depth, guarded, rec + 1)
                    if a is not _ERRORPATH:
                        arms.append(a)
                if not arms:
                    i += 1
                    continue
                if all(_items_equal(a, arms[0]) for a in arms[1:]):
                    out.extend(arms[0])
                else:
                    out.append(_OPAQUE_ITEM)
                i += 1
                continue
            i += 1
        return out

    @staticmethod
    def _is_error_return(s: Stmt) -> bool:
        if s.marker in _PY_RETURN_MACROS:
            return False
        vals = [
            t.value
            for t in s.tokens
            if not (t.kind == "id" and t.value == "return")
        ]
        if vals == ["NULL"] or vals == ["-", "1"]:
            return True
        if vals and vals[0] == "PyErr_NoMemory":
            return True
        return False

    @staticmethod
    def _is_guard(cond: List[Tok]) -> bool:
        text = " ".join(t.value for t in cond)
        return bool(_GUARD_RE.search(text))


def _items_equal(a: List[SchemaItem], b: List[SchemaItem]) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if (x.op, x.depth, x.guarded) != (y.op, y.depth, y.guarded):
            return False
    return True


def truncate_opaque(items: List[SchemaItem]) -> Tuple[List[SchemaItem], bool]:
    """Cut the sequence at the first opaque item; returns (items, truncated)."""
    for i, it in enumerate(items):
        if it.op == "<opaque>":
            return items[:i], True
    return items, False


# ---------------------------------------------------------------------------
# dispatcher extraction
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SchemaBranch:
    key: str  # MSG_* constant name (C spelling)
    items: Tuple[SchemaItem, ...]
    truncated: bool
    line: int
    fn_name: str


def encoder_branches(model: "NativeModel") -> Dict[str, SchemaBranch]:
    """Typed encode branches: top-level ifs whose flattened then-arm
    starts with a u8 emission of a MSG_* discriminator."""
    flat = _SchemaFlattener(model, "enc")
    out: Dict[str, SchemaBranch] = {}
    for fn in model.functions.values():
        if not fn.parsed:
            continue
        for s in fn.body:
            if s.kind != "If":
                continue
            seq = flat.flatten_stmts(
                [Stmt("Expr", s.line, tokens=s.cond)] + list(s.body),
                0,
                False,
                0,
            )
            if seq is _ERRORPATH or not seq:
                continue
            first = seq[0]
            if (
                first.op == "u8"
                and first.arg is not None
                and _MSG_CONST_RE.match(first.arg)
            ):
                items, truncated = truncate_opaque(seq[1:])
                out[first.arg] = SchemaBranch(
                    key=first.arg,
                    items=tuple(items),
                    truncated=truncated,
                    line=s.line,
                    fn_name=fn.name,
                )
    return out


def decoder_branches(model: "NativeModel") -> Dict[str, SchemaBranch]:
    """Typed decode branches: switch case-groups labelled case MSG_*."""
    flat = _SchemaFlattener(model, "dec")
    out: Dict[str, SchemaBranch] = {}
    for fn in model.functions.values():
        if not fn.parsed:
            continue
        for sw in _iter_switches(fn.body):
            for labs, body in sw.cases:
                keys = []
                for lab in labs:
                    v = _single_id(lab)
                    if v is not None and _MSG_CONST_RE.match(v):
                        keys.append(v)
                if not keys:
                    continue
                seq = flat.flatten_stmts(list(body), 0, False, 0)
                if seq is _ERRORPATH:
                    continue
                items, truncated = truncate_opaque(seq)
                line = body[0].line if body else sw.line
                for key in keys:
                    out[key] = SchemaBranch(
                        key=key,
                        items=tuple(items),
                        truncated=truncated,
                        line=line,
                        fn_name=fn.name,
                    )
    return out


def _iter_switches(stmts: Sequence[Stmt]):
    for s in stmts:
        if s.kind == "Switch":
            yield s
            for _labs, body in s.cases:
                yield from _iter_switches(body)
        if s.kind in ("Block", "If", "Loop"):
            yield from _iter_switches(s.body)
        if s.kind == "If":
            yield from _iter_switches(s.orelse)


# ---------------------------------------------------------------------------
# the model + per-file entry point
# ---------------------------------------------------------------------------


class NativeModel:
    """All extracted facts for one C/C++ source file."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tokens = tokenize(source)
        self.functions: Dict[str, CFunc] = {}
        for fn in extract_functions(self.tokens):
            self.functions.setdefault(fn.name, fn)
        self._consume_cache: Dict[str, Set[str]] = {}

    def may_consume(self, name: str) -> Set[str]:
        """Parameter names the in-file callee may Py_DECREF/CLEAR."""
        cached = self._consume_cache.get(name)
        if cached is not None:
            return cached
        fn = self.functions.get(name)
        out: Set[str] = set()
        if fn is not None:
            toks = fn.body_tokens
            for i, t in enumerate(toks):
                if (
                    t.kind == "id"
                    and t.value in ("Py_DECREF", "Py_XDECREF", "Py_CLEAR")
                    and i + 2 < len(toks)
                    and toks[i + 1].value == "("
                ):
                    args = _call_args(toks, i + 1)
                    if args:
                        v = _single_id(args[0])
                        if v in fn.params:
                            out.add(v)
        self._consume_cache[name] = out
        return out

    def refcount_leaks(self, fn: CFunc) -> List[RefLeak]:
        return _RC(fn, self).run()
