"""tpusan rules: device-residency invariants for the storage hot path.

Four rules ride the interprocedural lattice in
``analysis/residency_flow.py``:

* ``jax-d2h-in-resident-section`` -- a D2H transfer (explicit
  ``device_get`` seam, ``np.asarray``/``.tolist()``/``float()`` on a
  device value, iteration, or a call to a helper that transitively
  syncs) is reachable inside a declared ``# cephlint:
  device-resident-section <name>`` region.  The declaration is the
  storage path's roofline contract: inside the region bytes stay in
  HBM.  The same regions are enforced at runtime by
  ``analysis/residency.py`` (``jax.transfer_guard_device_to_host``
  under tier-1), so each section must also carry its
  ``resident_section(<name>)`` runtime guard.
* ``jax-recompile-hazard`` -- ``jax.jit`` constructed per call inside a
  function body, a shape-derived value (``x.shape[i]``, ``len(x)``)
  fed raw to a static parameter of a jitted kernel (one retrace per
  distinct size; the granule ladder exists so shapes are bucketed),
  or a bare Python scalar literal fed to a traced parameter.
* ``jax-donated-after-use`` -- a buffer passed at a
  ``donate_argnums`` position and read again on any CFG path after
  the call: donation hands the buffer to XLA, the read sees freed or
  aliased memory.
* ``jax-loop-invariant-transfer`` -- H2D (``device_put``/
  ``jnp.asarray``) of a loop-invariant value inside a loop, a D2H of a
  loop-invariant device value per iteration (Python iteration over a
  device array included), and the method-scope variant: per-call
  upload of instance-constant state (``jnp.asarray(self.B)`` outside
  ``__init__``) -- the exact shape that re-shipped the mesh codec's
  coding matrix on EVERY encode call.  Hoist onto the accounted upload
  cache (``ops/pipeline.py accounted_device_matrix``) or upload once
  at construction.

These subsume the retired shallow checks (``jax-host-sync-hot-path``,
``jax-device-array-iteration``): the lattice knows where a value lives,
so converting a HOST array in a loop is no longer noise and a device
array leaking through a helper is no longer invisible.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis import cfg as cfg_mod
from ceph_tpu.analysis import residency_flow as flow
from ceph_tpu.analysis.core import (SEV_ERROR, SEV_WARNING, FileContext,
                                    Finding, call_name, dotted_name,
                                    parse_resident_sections, rule)


def _wants_analysis(ctx: FileContext) -> bool:
    return ctx.imports_module("jax") or \
        "device-resident-section" in ctx.source


def _in_ceph_tpu(ctx: FileContext) -> bool:
    return ctx.path.startswith("ceph_tpu/")


# -- jit decoration parsing -------------------------------------------------


def _const_set(expr: ast.AST) -> Set:
    """Literal values of a tuple/list/single constant expression."""
    if isinstance(expr, ast.Constant):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        return {e.value for e in expr.elts if isinstance(e, ast.Constant)}
    return set()


def _jit_kwargs(call: ast.Call) -> Dict[str, Set]:
    out: Dict[str, Set] = {}
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames",
                      "donate_argnums"):
            out[kw.arg] = _const_set(kw.value)
    return out


def _is_jit_target(expr: ast.AST) -> bool:
    return dotted_name(expr).rsplit(".", 1)[-1] == "jit"


def _jit_spec(fn_node: ast.AST) -> Optional[Dict[str, Set]]:
    """{"static_argnums", "static_argnames", "donate_argnums"} sets when
    ``fn_node`` is decorated jitted, else None."""
    for dec in getattr(fn_node, "decorator_list", []):
        if isinstance(dec, ast.Call):
            if _is_jit_target(dec.func):
                return _jit_kwargs(dec)
            if dotted_name(dec.func).rsplit(".", 1)[-1] == "partial" and \
                    dec.args and _is_jit_target(dec.args[0]):
                return _jit_kwargs(dec)
        elif _is_jit_target(dec):
            return {}
    return None


def _params_of(fn_node: ast.AST) -> List[str]:
    args = fn_node.args
    params = [a.arg for a in getattr(args, "posonlyargs", [])] + \
             [a.arg for a in args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return params


# -- rule: D2H inside a declared device-resident section --------------------


@rule(
    "jax-d2h-in-resident-section", "jax", SEV_ERROR,
    "a D2H transfer (np.asarray / .tolist() / float() / iteration / "
    "device_get, or a helper that transitively syncs) is reachable "
    "inside a declared `cephlint: device-resident-section` region, or "
    "the markers are malformed / missing their runtime "
    "resident_section() guard.  The region declares that bytes stay in "
    "HBM; the runtime verifier (analysis/residency.py) enforces the "
    "same contract under tier-1 with jax.transfer_guard",
)
def check_d2h_in_resident_section(ctx: FileContext) -> Iterator[Finding]:
    if "device-resident-section" not in ctx.source:
        return
    sections, problems = parse_resident_sections(ctx.lines)
    for line, message in problems:
        yield Finding("jax-d2h-in-resident-section", ctx.path, line, 0,
                      message, SEV_ERROR)
    if not sections:
        return
    analysis = flow.get(ctx)
    # each declared region must pair with its runtime guard: a
    # resident_section("<name>") call between the markers (the static
    # markers and the transfer_guard scope must cover the same lines)
    guarded: Set[str] = set()
    for node in ast.walk(analysis.ctx.tree):
        if isinstance(node, ast.Call) and \
                call_name(node).rsplit(".", 1)[-1] == "resident_section" \
                and node.args and isinstance(node.args[0], ast.Constant):
            for s in sections:
                if s.start < node.lineno < s.end and \
                        node.args[0].value == s.name:
                    guarded.add(s.name)
    for s in sections:
        if s.name not in guarded:
            yield Finding(
                "jax-d2h-in-resident-section", ctx.path, s.start, 0,
                f"device-resident-section {s.name!r} has no matching "
                f"runtime guard: wrap the region's body in "
                f"`with resident_section({s.name!r}):` "
                "(ceph_tpu.analysis.residency) so the declaration is "
                "enforced, not trusted", SEV_ERROR)
    for fr in analysis.functions.values():
        for site in fr.sync_sites:
            line = getattr(site.node, "lineno", None)
            if line is None:
                continue
            section = next(
                (s for s in sections if s.start < line < s.end), None)
            if section is None:
                continue
            yield ctx.finding(
                "jax-d2h-in-resident-section", site.node,
                f"D2H transfer inside device-resident-section "
                f"{section.name!r} (lines {section.start}-{section.end}):"
                f" {site.desc}; the section declares this stretch "
                "device-resident -- move the sync to the section "
                "boundary or keep the value on device",
            )


# -- rule: recompile hazards ------------------------------------------------


def _contains_shape_probe(expr: ast.AST) -> bool:
    """The expression derives from a runtime shape: x.shape[i], len(x),
    or x.size."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in ("shape",
                                                            "size"):
            return True
        if isinstance(node, ast.Call) and call_name(node) == "len":
            return True
    return False


def _is_bucketed(expr: ast.AST) -> bool:
    """Routed through the sanctioned batch-shape bucketing idiom: a call
    whose name mentions the granule ladder (rung/bucket/ladder/tile),
    or a min()/max() cap against a constant (the ladder's last step)."""
    if not isinstance(expr, ast.Call):
        return False
    name = call_name(expr).rsplit(".", 1)[-1].lower()
    if any(h in name for h in ("rung", "bucket", "ladder", "tile")):
        return True
    if name in ("min", "max"):
        return any(isinstance(a, ast.Constant) for a in expr.args)
    return False


@rule(
    "jax-recompile-hazard", "jax", SEV_WARNING,
    "per-call jax.jit construction, a raw shape-derived value fed to a "
    "static parameter of a jitted kernel (one XLA compile per distinct "
    "size -- route it through the batch-shape bucketing helper / a "
    "constant cap), or a Python scalar literal fed to a traced "
    "parameter (weak-typed scalars promote per call site; make it "
    "static or ship an array)",
)
def check_recompile_hazard(ctx: FileContext) -> Iterator[Finding]:
    if not _in_ceph_tpu(ctx) or not ctx.imports_module("jax"):
        return
    analysis = flow.get(ctx)
    actx = analysis.ctx
    parents = actx.parent_map()

    def _in_decorator(node: ast.AST) -> bool:
        cur = node
        while cur in parents:
            parent = parents[cur]
            decs = getattr(parent, "decorator_list", [])
            if any(cur is d for d in decs):
                return True
            cur = parent
        return False

    def _enclosing_fn(node: ast.AST):
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None

    # (1) per-call jit construction inside a function body
    for node in ast.walk(actx.tree):
        if not (isinstance(node, ast.Call) and _is_jit_target(node.func)):
            continue
        if _enclosing_fn(node) is None or _in_decorator(node):
            continue  # module-level / decorator position: compiled once
        stmt = node
        while stmt in parents and not isinstance(stmt, ast.stmt):
            stmt = parents[stmt]
        # sanctioned caching shapes: `return jax.jit(f)` from a builder
        # (the caller caches the result) and `self._fn = jax.jit(f)`
        if isinstance(stmt, ast.Return):
            continue
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Attribute) for t in stmt.targets):
            continue
        yield ctx.finding(
            "jax-recompile-hazard", node,
            "jax.jit(...) constructed inside a function body: every "
            "call builds a fresh jitted callable with an empty compile "
            "cache; build it once (module level, __init__, or a cached "
            "builder)",
        )

    # (2)/(3) call sites of module-local jitted kernels
    jitted: Dict[str, Tuple[Dict[str, Set], List[str]]] = {}
    for qual, fr in analysis.functions.items():
        spec = _jit_spec(fr.info.node)
        if spec is not None:
            jitted[qual] = (spec, _params_of(fr.info.node))
    if not jitted:
        return
    for fr in analysis.functions.values():
        for node in ast.walk(fr.info.node):
            if not isinstance(node, ast.Call):
                continue
            qual = analysis.graph._resolve_call(fr.info, node)
            if qual not in jitted:
                continue
            spec, params = jitted[qual]
            static_nums = spec.get("static_argnums", set())
            static_names = spec.get("static_argnames", set())
            for idx, arg in enumerate(node.args):
                pname = params[idx] if idx < len(params) else None
                is_static = idx in static_nums or pname in static_names
                if is_static:
                    if _contains_shape_probe(arg) and \
                            not _is_bucketed(arg):
                        yield ctx.finding(
                            "jax-recompile-hazard", arg,
                            f"shape-derived value fed raw to static "
                            f"parameter {pname or idx!r} of jitted "
                            f"{qual}(): one XLA compile per distinct "
                            "size; bucket it (granule ladder / "
                            "min(cap, n))",
                        )
                elif isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, (int, float)) and \
                        not isinstance(arg.value, bool):
                    yield ctx.finding(
                        "jax-recompile-hazard", arg,
                        f"Python scalar literal fed to traced parameter "
                        f"{pname or idx!r} of jitted {qual}(): weak-"
                        "typed scalars re-promote per call site and a "
                        "dtype flip retraces; make the parameter "
                        "static_argnums or pass a device array",
                    )
            for kw in node.keywords:
                if kw.arg in static_names and \
                        _contains_shape_probe(kw.value) and \
                        not _is_bucketed(kw.value):
                    yield ctx.finding(
                        "jax-recompile-hazard", kw.value,
                        f"shape-derived value fed raw to static "
                        f"parameter {kw.arg!r} of jitted {qual}(): one "
                        "XLA compile per distinct size; bucket it",
                    )


# -- rule: donated buffer read after the call -------------------------------


def _stmt_of(node: ast.AST, parents) -> Optional[ast.stmt]:
    cur = node
    while cur in parents and not isinstance(cur, ast.stmt):
        cur = parents[cur]
    return cur if isinstance(cur, ast.stmt) else None


def _own_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.stmt):
            continue
        for node in ast.walk(child):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node


def _reads_name(stmt: ast.stmt, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name and
               isinstance(n.ctx, ast.Load) for n in _own_exprs(stmt))


def _rebinds_name(stmt: ast.stmt, name: str) -> bool:
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and n.id == name:
                return True
    return False


@rule(
    "jax-donated-after-use", "jax", SEV_ERROR,
    "a buffer passed at a donate_argnums position is read again on a "
    "CFG path after the donating call: donation hands the buffer's "
    "memory to XLA (the in-place update optimization), so the read "
    "observes freed or aliased storage.  Re-derive the value from the "
    "call's RESULT, or drop the donation",
)
def check_donated_after_use(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.imports_module("jax"):
        return
    analysis = flow.get(ctx)
    actx = analysis.ctx
    parents = actx.parent_map()
    # donors: decorated defs and names bound to jax.jit(f, donate_...)
    donate_of: Dict[str, Set[int]] = {}
    for qual, fr in analysis.functions.items():
        spec = _jit_spec(fr.info.node)
        if spec and spec.get("donate_argnums"):
            donate_of[fr.info.node.name] = spec["donate_argnums"]
    for node in ast.walk(actx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                _is_jit_target(node.value.func):
            kw = _jit_kwargs(node.value)
            if kw.get("donate_argnums"):
                donate_of[node.targets[0].id] = kw["donate_argnums"]
    if not donate_of:
        return
    cfg_cache: Dict[int, cfg_mod.CFG] = {}
    for fr in analysis.functions.values():
        for node in ast.walk(fr.info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = call_name(node).rsplit(".", 1)[-1]
            donated = donate_of.get(fname)
            if not donated:
                continue
            donated_names = [
                (idx, arg.id) for idx, arg in enumerate(node.args)
                if idx in donated and isinstance(arg, ast.Name)
            ]
            if not donated_names:
                continue
            fcfg = cfg_cache.get(id(fr.info.node))
            if fcfg is None:
                fcfg = cfg_mod.build(fr.info.node)
                cfg_cache[id(fr.info.node)] = fcfg
            call_stmt = _stmt_of(node, parents)
            if call_stmt is None or call_stmt not in fcfg.succ:
                continue
            for idx, name in donated_names:
                if _rebinds_name(call_stmt, name):
                    continue  # `buf = donor(buf)`: later reads are fresh
                hit = _first_read_after(fcfg, call_stmt, name)
                if hit is not None:
                    yield ctx.finding(
                        "jax-donated-after-use", hit,
                        f"{name!r} was donated to {fname}() on line "
                        f"{node.lineno} (donate_argnums position "
                        f"{idx}) and is read again here: the buffer "
                        "now belongs to XLA -- use the call's result "
                        "or drop the donation",
                    )


def _first_read_after(fcfg: cfg_mod.CFG, src: ast.stmt,
                      name: str) -> Optional[ast.stmt]:
    """First CFG-reachable statement reading ``name`` with no rebind of
    it on the path (a rebind makes later reads fresh)."""
    seen: Set[int] = set()
    frontier: List[object] = list(fcfg.succ.get(src, []))
    while frontier:
        node = frontier.pop()
        if node is cfg_mod.EXIT or id(node) in seen or node is src:
            continue
        seen.add(id(node))
        if _reads_name(node, name):  # type: ignore[arg-type]
            return node  # type: ignore[return-value]
        if _rebinds_name(node, name):  # type: ignore[arg-type]
            continue  # fresh value past this point
        frontier.extend(fcfg.succ.get(node, []))
    return None


# -- rule: loop-invariant transfers -----------------------------------------

#: explicit H2D spellings (device-producer calls that ship host bytes)
_H2D_CALLS = {
    "jax.device_put", "jax.device_put_sharded", "jax.device_put_replicated",
    "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
    "residency.device_put", "residency.to_device", "_to_device",
}


def _assigned_names(stmts: List[ast.stmt]) -> Tuple[Set[str], Set[str]]:
    """(names, self-attrs) stored anywhere under ``stmts``."""
    names: Set[str] = set()
    attrs: Set[str] = set()
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.Name,)) and isinstance(node.ctx,
                                                        ast.Store):
            names.add(node.id)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Store) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            attrs.add(node.attr)
        stack.extend(ast.iter_child_nodes(node))
    return names, attrs


def _invariant_operand(expr: ast.AST, loop_names: Set[str],
                       loop_attrs: Set[str]) -> Optional[str]:
    """Spelling of ``expr`` when it provably does not change across loop
    iterations: a Name never stored in the loop, or a self.<attr> never
    stored in the loop."""
    if isinstance(expr, ast.Name) and expr.id not in loop_names:
        return expr.id
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self" and expr.attr not in loop_attrs:
        return f"self.{expr.attr}"
    return None


@rule(
    "jax-loop-invariant-transfer", "jax", SEV_WARNING,
    "an H2D upload (device_put / jnp.asarray) or D2H pull of a value "
    "that does not change across iterations sits inside a loop (or a "
    "per-call upload of instance-constant state like jnp.asarray(self.B)"
    " outside __init__): the same bytes cross the bus every pass.  "
    "Hoist it out, or route codec matrices through the accounted upload"
    " cache (ops/pipeline.py accounted_device_matrix)",
)
def check_loop_invariant_transfer(ctx: FileContext) -> Iterator[Finding]:
    if not _in_ceph_tpu(ctx) or not ctx.imports_module("jax"):
        return
    analysis = flow.get(ctx)
    reported: Set[Tuple[int, int]] = set()

    def _once(node: ast.AST) -> bool:
        mark = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if mark in reported:
            return False
        reported.add(mark)
        return True

    for fr in analysis.functions.values():
        fn_node = fr.info.node
        # iteration over a device array: per-element D2H of a value the
        # loop itself does not change (the retired
        # jax-device-array-iteration class, now lattice-aware)
        for node in flow.ModuleResidency._own_stmts_and_exprs(fn_node):
            if isinstance(node, (ast.For, ast.AsyncFor)) and \
                    analysis.expr_res(fr, node.iter) == DEVICE_LATTICE \
                    and _once(node):
                yield ctx.finding(
                    "jax-loop-invariant-transfer", node,
                    "Python for-loop iterates a device array element-"
                    "wise: every element is a separate blocking D2H; "
                    "pull it to host once (device_get) outside the "
                    "loop or vectorize the body",
                )
        # per-call upload of instance state (no loop needed: the caller
        # IS the loop -- the mesh-codec self.B class)
        if fn_node.name not in ("__init__", "__post_init__", "__new__"):
            for node in flow.ModuleResidency._own_stmts_and_exprs(fn_node):
                if isinstance(node, ast.Call) and \
                        call_name(node) in _H2D_CALLS and node.args:
                    op = node.args[0]
                    if isinstance(op, ast.Attribute) and \
                            isinstance(op.value, ast.Name) and \
                            op.value.id == "self" and _once(node):
                        yield ctx.finding(
                            "jax-loop-invariant-transfer", node,
                            f"per-call H2D of instance state "
                            f"self.{op.attr}: every call re-ships the "
                            "same bytes; upload once in __init__ or "
                            "route through accounted_device_matrix "
                            "(ops/pipeline.py)",
                        )
        # lexical loops: invariant H2D / invariant-device D2H inside
        for loop in flow.ModuleResidency._own_stmts_and_exprs(fn_node):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            body = list(loop.body) + list(getattr(loop, "orelse", []))
            loop_names, loop_attrs = _assigned_names(body)
            if isinstance(loop, (ast.For, ast.AsyncFor)):
                for n in ast.walk(loop.target):
                    if isinstance(n, ast.Name):
                        loop_names.add(n.id)
            for node in _loop_own_nodes(body):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = call_name(node)
                op = node.args[0]
                inv = _invariant_operand(op, loop_names, loop_attrs)
                if inv is None:
                    continue
                if name in _H2D_CALLS:
                    if not _once(node):
                        continue
                    yield ctx.finding(
                        "jax-loop-invariant-transfer", node,
                        f"H2D upload of loop-invariant {inv} inside a "
                        f"loop (line {loop.lineno}): the same bytes "
                        "cross the bus every iteration; hoist the "
                        "transfer (or the accounted matrix cache) out",
                    )
                elif (name in flow.EXPLICIT_D2H_CALLS or
                        name in flow.IMPLICIT_SINK_CALLS) and \
                        analysis.expr_res(fr, op) == DEVICE_LATTICE and \
                        _once(node):
                    yield ctx.finding(
                        "jax-loop-invariant-transfer", node,
                        f"D2H pull of loop-invariant device value {inv} "
                        f"inside a loop (line {loop.lineno}): pull once"
                        " outside the loop",
                    )


DEVICE_LATTICE = flow.DEVICE


# -- rule: per-call Mesh / NamedSharding / PartitionSpec construction -------


#: jax placement-object constructors (plus the repo's make_mesh helper);
#: ImportFrom aliases (``PartitionSpec as P``) are resolved per file
_SHARDING_CTORS = {"Mesh", "NamedSharding", "PartitionSpec", "make_mesh"}


def _sharding_aliases(ctx: FileContext) -> Set[str]:
    names = set(_SHARDING_CTORS)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name in _SHARDING_CTORS and a.asname:
                    names.add(a.asname)
    return names


@rule(
    "jax-percall-sharding-construction", "jax", SEV_WARNING,
    "a Mesh / NamedSharding / PartitionSpec (or make_mesh) is "
    "constructed inside a loop or inside a jitted dispatch path: "
    "placement objects are dispatch-invariant, and rebuilding one per "
    "call re-hashes device lists and defeats jax's C++ dispatch cache "
    "(the mesh analogue of jax-loop-invariant-transfer).  Build once "
    "and cache content-keyed -- the mesh plane's sharding()/pspec() "
    "caches (parallel/mesh_plane.py) are the blessed seam",
)
def check_percall_sharding_construction(
    ctx: FileContext,
) -> Iterator[Finding]:
    if not _in_ceph_tpu(ctx) or not ctx.imports_module("jax"):
        return
    names = _sharding_aliases(ctx)
    parents = ctx.parent_map()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node).rsplit(".", 1)[-1] not in names:
            continue
        # ancestry walk: the nearest enclosing loop or jitted function
        # decides; a construction in plain builder code (codec
        # __init__, cache-miss fill) is the sanctioned shape
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                yield ctx.finding(
                    "jax-percall-sharding-construction", node,
                    f"{call_name(node)} constructed inside a loop "
                    f"(line {cur.lineno}): placement objects are "
                    "loop-invariant -- build once outside (or through "
                    "a content-keyed cache like mesh_plane.sharding())",
                )
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _jit_spec(cur) is not None:
                    yield ctx.finding(
                        "jax-percall-sharding-construction", node,
                        f"{call_name(node)} constructed inside jitted "
                        f"function {cur.name}: sharding objects belong "
                        "outside the traced computation -- close over "
                        "a cached instance instead",
                    )
                # a function boundary ends the ancestry either way: an
                # enclosing loop re-runs the DEF, not the body
                break
            cur = parents.get(cur)


def _loop_own_nodes(body: List[ast.stmt]) -> Iterator[ast.AST]:
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
