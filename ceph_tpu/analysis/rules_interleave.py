"""Flow-aware await-interleaving rules (asyncsan).

Three of the last four PRs shipped a concurrency bug the per-function
pattern rules structurally could not see:

* PR 2: an innocent-looking ``await`` opened a yield window between the
  OSD's TCP listen and ``host_pool`` -- revived peers' replayed sub-ops
  dispatched into a pool-less shard ("hosts no pool");
* PR 3: the messenger's receive watermark advanced BEFORE a
  tear-capable await -- a connection dying inside that await marked an
  undelivered message delivered and the reconnect replay skipped it;
* PR 5: the whole exactly-once effort exists because client-op state
  mutations interleave across awaits.

All three are the same shape: a shared-state invariant that holds only
if no task switch lands inside a region, broken by an await (sometimes
hidden inside a helper).  These rules walk each async function's CFG
(``analysis/cfg.py``) with the module call graph's may-await summaries
(``analysis/callgraph.py``) so a task-switch point is recognized even
when it hides behind a ``self._helper()`` call, while an await of a
helper that provably cannot suspend stays clean.

Rules:

* ``async-rmw-across-await`` -- read-modify-write of ``self.*`` /
  ``global`` state split across a task-switch point: stale-read
  carriers (``v = self.x`` ... yield ... ``self.x = f(v)``), one-statement
  RMWs whose value awaits (``self.x = merge(self.x, await f())``,
  ``self.x += await f()``), and check-then-act (a branch tested on
  ``self.x``, a yield, then a store to ``self.x``).  Spans bridged
  entirely inside one ``async with ...lock:`` block are exempt -- the
  lock IS the sanctioned way to hold state across awaits.
* ``async-lock-across-await`` -- an explicitly acquired lock or
  budget/ledger token (``await x.acquire()``, ``await throttle.get(n)``)
  held across a task-switch point with no try/finally releasing it:
  the failure path leaks the token and every later acquirer parks
  forever.
* ``async-atomic-section`` -- a declared yield-free region (comment
  markers ``cephlint: atomic-section <name>`` ... ``cephlint:
  end-atomic-section``) containing any task-switch point, plus
  malformed marker pairs.  The same declarations are enforced at
  runtime by ``analysis/runtime.py`` under tier-1, so the annotation
  is tested, not trusted.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis import callgraph as callgraph_mod
from ceph_tpu.analysis import cfg as cfg_mod
from ceph_tpu.analysis.core import (SEV_ERROR, FileContext, Finding,
                                    dotted_name, parse_atomic_sections,
                                    rule)

# -- shared helpers --------------------------------------------------------

#: state key: ("self", attr) or ("global", name)
_Key = Tuple[str, str]


def _state_reads(stmt: ast.stmt, globals_: Set[str]) -> Set[_Key]:
    """State keys read anywhere in ``stmt``'s own expressions."""
    out: Set[_Key] = set()
    for node in _own_exprs(stmt):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            out.add(("self", node.attr))
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and node.id in globals_:
            out.add(("global", node.id))
    return out


def _own_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Expression nodes evaluated by ``stmt`` itself (compound bodies
    and nested defs excluded)."""
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.stmt):
            continue
        for node in ast.walk(child):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node


def _store_key(target: ast.expr, globals_: Set[str]) -> Optional[_Key]:
    """The state key a store target writes: ``self.x``, ``self.x[k]``,
    or a ``global``-declared name."""
    node = target
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return ("self", node.attr)
    if isinstance(node, ast.Name) and node.id in globals_:
        return ("global", node.id)
    return None


def _stmt_writes(stmt: ast.stmt,
                 globals_: Set[str]) -> List[Tuple[_Key, ast.expr]]:
    """(key, value-expr) for each state store in this statement."""
    out: List[Tuple[_Key, ast.expr]] = []
    if isinstance(stmt, ast.Assign):
        for target in stmt.targets:
            elts = target.elts if isinstance(
                target, (ast.Tuple, ast.List)) else [target]
            for elt in elts:
                key = _store_key(elt, globals_)
                if key is not None:
                    out.append((key, stmt.value))
    elif isinstance(stmt, ast.AugAssign):
        key = _store_key(stmt.target, globals_)
        if key is not None:
            out.append((key, stmt.value))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        key = _store_key(stmt.target, globals_)
        if key is not None:
            out.append((key, stmt.value))
    return out


def _declared_globals(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _mentions_lock(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Call):
        return _mentions_lock(expr.func)
    return dotted_name(expr).rsplit(".", 1)[-1].lower().endswith("lock")


def _lock_span(ctx: FileContext, a: ast.AST, b: ast.AST) -> bool:
    """Both nodes sit inside the SAME ``async with ...lock:`` block --
    the sanctioned hold-state-across-awaits pattern."""
    parents = ctx.parent_map()

    def lock_withs(node: ast.AST) -> List[ast.AST]:
        chain = []
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(cur, ast.AsyncWith) and any(
                    _mentions_lock(item.context_expr)
                    for item in cur.items):
                chain.append(cur)
        return chain

    spans_a = lock_withs(a)
    return bool(spans_a) and any(w in lock_withs(b) for w in spans_a)


def _function_cfg_and_yields(graph, info):
    """(cfg, yield statement set) for one async function."""
    fcfg = cfg_mod.build(info.node)
    yields: Set[ast.stmt] = set()
    for stmt in fcfg.stmts:
        if graph.stmt_yield_node(info, stmt) is not None:
            yields.add(stmt)
    return fcfg, yields


# -- rule: read-modify-write across a task-switch point --------------------

@rule(
    "async-rmw-across-await", "async", SEV_ERROR,
    "read-modify-write of self.*/module state split across an await (or "
    "a call to a helper that may await): another task can mutate the "
    "state inside the yield window and the write clobbers it -- the "
    "PR-3 watermark class.  Interprocedural: a helper that only "
    "transitively sleeps still counts; an async helper that provably "
    "never yields does not.",
)
def check_rmw_across_await(ctx: FileContext) -> Iterator[Finding]:
    graph = callgraph_mod.get(ctx)
    for info in graph.functions.values():
        if not info.is_async:
            continue
        globals_ = _declared_globals(info.node)
        fcfg, yields = _function_cfg_and_yields(graph, info)
        if not yields:
            continue  # no task-switch point: nothing can interleave

        # carriers: local = <expr reading state key>
        carriers: List[Tuple[str, _Key, ast.stmt]] = []
        guards: List[Tuple[_Key, ast.stmt]] = []
        for stmt in fcfg.stmts:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                for key in _state_reads(stmt, globals_):
                    carriers.append((stmt.targets[0].id, key, stmt))
            if isinstance(stmt, (ast.If, ast.While)):
                test_reads: Set[_Key] = set()
                for node in ast.walk(stmt.test):
                    if isinstance(node, ast.Attribute) and \
                            isinstance(node.ctx, ast.Load) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id == "self":
                        test_reads.add(("self", node.attr))
                for key in test_reads:
                    guards.append((key, stmt))

        reported: Set[Tuple[int, str]] = set()

        def report(stmt: ast.stmt, key: _Key, how: str):
            label = key[1] if key[0] == "self" else key[1]
            spell = f"self.{key[1]}" if key[0] == "self" else key[1]
            mark = (stmt.lineno, label)
            if mark in reported:
                return None
            reported.add(mark)
            return ctx.finding(
                "async-rmw-across-await", stmt,
                f"write to {spell} completes a read-modify-write whose "
                f"read happened before a task-switch point ({how}); "
                "another task can update the state inside that window "
                "and this write clobbers it -- recompute from the live "
                "value after the await, hold an asyncio lock across the "
                "span, or declare the region atomic and move the await "
                "out",
            )

        for stmt in fcfg.stmts:
            for key, value in _stmt_writes(stmt, globals_):
                # same-statement: the value both reads the key and
                # crosses a yield before the store lands
                yield_node = graph.expr_yield_node(info, value)
                if yield_node is not None:
                    reads_key = isinstance(stmt, ast.AugAssign) or \
                        key in _state_reads(stmt, globals_)
                    if reads_key:
                        f = report(stmt, key,
                                   "the awaited expression in this very "
                                   "statement")
                        if f:
                            yield f
                        continue
                # a guard on the same key with a YIELD-FREE path into
                # this write is a fresh re-check (the sanctioned
                # re-check-after-await fix): the write acts on live
                # state, not the stale pre-await read
                fresh_check = any(
                    gkey == key and gstmt is not stmt and
                    fcfg.reaches_clean(gstmt, stmt, yields)
                    for gkey, gstmt in guards)
                # carrier pattern: v = f(self.x) ... yield ... self.x = g(v)
                hit = False
                if not fresh_check:
                    value_names = _names_in(value)
                    # a write that ALSO re-reads the key is a fresh
                    # merge (max/extend against the live value), not a
                    # blind clobber of it
                    fresh_merge = key in _state_reads(stmt, globals_) \
                        and not isinstance(stmt, ast.AugAssign)
                    for name, ckey, cstmt in carriers:
                        if fresh_merge or ckey != key or \
                                name not in value_names or cstmt is stmt:
                            continue
                        crossed = fcfg.crosses_yield(
                            cstmt, stmt, yields,
                            start_crossed=graph.stmt_yield_node(
                                info, cstmt) is not None)
                        if crossed and not _lock_span(ctx, cstmt, stmt):
                            f = report(
                                stmt, key,
                                f"read into {name!r} on line "
                                f"{cstmt.lineno}")
                            if f:
                                yield f
                                hit = True
                            break
                if hit or fresh_check:
                    continue
                # check-then-act: `if self.x ...:` ... yield ... store
                for gkey, gstmt in guards:
                    if gkey != key or gstmt is stmt:
                        continue
                    if fcfg.crosses_yield(gstmt, stmt, yields) and \
                            not _lock_span(ctx, gstmt, stmt):
                        f = report(
                            stmt, key,
                            f"guard tested on line {gstmt.lineno}")
                        if f:
                            yield f
                        break


# -- rule: lock/token held across a task-switch point ----------------------

#: awaited ``<base>.get(...)`` counts as a token acquisition only for
#: bases that look like admission budgets (queues also have .get)
_TOKEN_HINTS = ("throttle", "budget", "ledger", "quota")
_LOCK_HINTS = ("lock", "sem", "semaphore")


def _acquisition(stmt: ast.stmt) -> Optional[Tuple[str, ast.AST]]:
    """(dotted base, site node) when this statement acquires a lock or
    admission token it must later release."""
    for node in _own_exprs(stmt):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute)):
            continue
        base = dotted_name(node.func.value)
        tail = base.rsplit(".", 1)[-1].lower()
        if node.func.attr == "acquire":
            if any(h in tail for h in _LOCK_HINTS + _TOKEN_HINTS):
                return base, node
        elif node.func.attr == "get":
            if any(h in tail for h in _TOKEN_HINTS):
                return base, node
    return None


def _releases(stmt: ast.stmt, base: str) -> bool:
    for node in _own_exprs(stmt):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("release", "put") and \
                dotted_name(node.func.value) == base:
            return True
    return False


def _finally_releases(ctx: FileContext, stmt: ast.stmt, base: str) -> bool:
    """The acquisition is covered by a try/finally that releases: either
    an enclosing Try's finalbody releases, or the statement directly
    following the acquisition is such a Try."""
    parents = ctx.parent_map()

    def final_has_release(try_node: ast.Try) -> bool:
        for inner in try_node.finalbody:
            for sub in ast.walk(inner):
                if isinstance(sub, ast.stmt) and _releases(sub, base):
                    return True
        return False

    cur: ast.AST = stmt
    while cur in parents:
        parent = parents[cur]
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(parent, ast.Try) and cur in parent.body and \
                final_has_release(parent):
            return True
        cur = parent
    # `await x.acquire()` immediately followed by `try: ... finally: release`
    parent = parents.get(stmt)
    body = getattr(parent, "body", None)
    if isinstance(body, list) and stmt in body:
        idx = body.index(stmt)
        if idx + 1 < len(body) and isinstance(body[idx + 1], ast.Try) and \
                final_has_release(body[idx + 1]):
            return True
    return False


@rule(
    "async-lock-across-await", "async", SEV_ERROR,
    "a lock or budget/ledger token is acquired (`await x.acquire()`, "
    "`await throttle.get(n)`) and a task-switch point is reachable "
    "before any release, with no try/finally releasing it: an exception "
    "or cancellation landing in that window leaks the token and every "
    "later acquirer parks forever -- use `async with`, or wrap the span "
    "in try/finally",
)
def check_lock_across_await(ctx: FileContext) -> Iterator[Finding]:
    graph = callgraph_mod.get(ctx)
    for info in graph.functions.values():
        if not info.is_async:
            continue
        fcfg, yields = _function_cfg_and_yields(graph, info)
        if not yields:
            continue
        for stmt in fcfg.stmts:
            acq = _acquisition(stmt)
            if acq is None:
                continue
            base, site = acq
            if _finally_releases(ctx, stmt, base):
                continue
            stops = {s for s in fcfg.stmts if _releases(s, base)}
            hit = fcfg.first_yield_before(stmt, stops, yields)
            if hit is not None:
                yield ctx.finding(
                    "async-lock-across-await", site,
                    f"{base} is held at the task-switch point on line "
                    f"{hit.lineno} with no try/finally release on the "
                    "path; a failure in that window leaks the token "
                    "(use `async with`, or release in a finally)",
                )


# -- rule: declared atomic sections ----------------------------------------

@rule(
    "async-atomic-section", "async", SEV_ERROR,
    "a declared yield-free region (comment markers `cephlint: "
    "atomic-section <name>` ... `cephlint: end-atomic-section`) "
    "contains a task-switch point, or the markers are malformed.  The "
    "declaration is an invariant other code relies on "
    "(listen->host_pool, watermark ordering); the runtime verifier "
    "(analysis/runtime.py) enforces the same contract under tier-1.",
)
def check_atomic_sections(ctx: FileContext) -> Iterator[Finding]:
    sections, problems = parse_atomic_sections(ctx.lines)
    for line, message in problems:
        yield Finding("async-atomic-section", ctx.path, line, 0,
                      message, SEV_ERROR)
    if not sections:
        return
    graph = callgraph_mod.get(ctx)
    for info in graph.functions.values():
        if not info.is_async:
            continue
        for node in callgraph_mod._own_nodes(info.node):
            hit_line = getattr(node, "lineno", None)
            if hit_line is None:
                continue
            section = next(
                (s for s in sections if s.start < hit_line < s.end), None)
            if section is None:
                continue
            reason = None
            if isinstance(node, ast.Await):
                target = node.value
                callee = graph._resolve_call(info, target) \
                    if isinstance(target, ast.Call) else None
                if callee is None:
                    reason = "awaits outside-module code"
                else:
                    tinfo = graph.functions.get(callee)
                    if tinfo is None or not tinfo.is_async:
                        reason = f"awaits unresolved callee {callee!r}"
                    elif tinfo.may_await:
                        reason = (f"awaits {callee}(), which may "
                                  "suspend (transitively awaits)")
            elif isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                reason = "async for/with suspends at the protocol calls"
            if reason is not None:
                yield ctx.finding(
                    "async-atomic-section", node,
                    f"task-switch point inside atomic section "
                    f"{section.name!r} (lines {section.start}-"
                    f"{section.end}): {reason}; the section declares "
                    "this stretch yield-free -- move the await out or "
                    "re-establish the invariant after it",
                )
