"""Elastic-membership map-application rule.

``osdmap-apply-unguarded``: every OSDMap broadcast consumer must go
through :func:`ceph_tpu.mon.osdmap.apply_map_view`, which (a) gates on
the committed epoch so a stale or replayed broadcast can never rewind
placement, (b) GROWS the crush map for osd ids past ``n_osds`` (the
pre-elastic fixed-size ``weights[]`` push IndexError'd on the first
``osd add``), and (c) zeroes ids absent from the broadcast so ``osd
rm`` actually drains.  A raw weight-push loop over a map dict --

    for osd_id, w in m["weights"].items():
        placement.weights[int(osd_id)] = w

-- silently reimplements none of those three, so any function that
applies an osdmap's weight table by hand without calling
``apply_map_view`` is flagged.  ``mon/osdmap.py`` itself (the one
legitimate raw-push site, inside apply_map_view) is excluded by path.

Pure AST, like every cephlint rule.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ceph_tpu.analysis.core import (SEV_ERROR, FileContext, Finding,
                                    call_attr, rule)

#: the blessed applicator; a function that calls it may still loop over
#: the dict for bookkeeping (logging, census) without being flagged
_APPLICATOR = "apply_map_view"


def _weights_table(node: ast.expr) -> bool:
    """``X["weights"]`` / ``X.get("weights", ...)`` -- the raw weight
    table of an osdmap broadcast dict."""
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == "weights"
    if isinstance(node, ast.Call) and call_attr(node) == "get" and \
            node.args and isinstance(node.args[0], ast.Constant) and \
            node.args[0].value == "weights":
        return True
    return False


def _iterates_weights(it: ast.expr) -> bool:
    """The loop walks a broadcast's weight table, directly or via
    ``.items()``/``.keys()``."""
    if _weights_table(it):
        return True
    if isinstance(it, ast.Call) and call_attr(it) in ("items", "keys") \
            and isinstance(it.func, ast.Attribute):
        return _weights_table(it.func.value)
    return False


def _pushes_weight(loop: ast.For) -> Optional[ast.AST]:
    """First statement in the loop body that writes a placement weight
    slot (``<anything>.weights[...] = ...``, incl. augmented)."""
    for node in ast.walk(loop):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = (node.target,)
        for t in targets:
            if isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Attribute) and \
                    t.value.attr == "weights":
                return node
    return None


def _scope_calls_applicator(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and call_attr(node) == _APPLICATOR:
            return True
    return False


@rule(
    "osdmap-apply-unguarded",
    "ceph",
    SEV_ERROR,
    "osdmap weight table applied by a raw push loop instead of "
    "apply_map_view: no epoch gate (stale broadcasts rewind placement), "
    "no growth for new osd ids (IndexError on the first osd add), no "
    "zeroing of removed ids (osd rm never drains)",
)
def check_osdmap_apply_unguarded(ctx: FileContext) -> Iterator[Finding]:
    path = ctx.path.replace("\\", "/")
    if path.endswith("mon/osdmap.py"):
        return
    parents = ctx.parent_map()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.For):
            continue
        if not _iterates_weights(node.iter):
            continue
        if _pushes_weight(node) is None:
            continue
        # the raw push is fine only when its OWN enclosing function
        # (or the module body, for top-level code) also routes the
        # broadcast through apply_map_view
        scope: ast.AST = node
        while scope in parents and not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = parents[scope]
        if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = ctx.tree
        if _scope_calls_applicator(scope):
            continue
        yield ctx.finding(
            "osdmap-apply-unguarded", node,
            "raw osdmap weight push: route this broadcast through "
            "apply_map_view (epoch gate + crush growth + removed-id "
            "zeroing) instead of assigning weights[] by hand")
