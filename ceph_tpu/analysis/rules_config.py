"""Ceph invariant rules: the options registry and encode/decode pairing.

* ``ceph-config-undeclared-key``: the reference declares every option
  once in src/common/options.cc; readers then cannot drift from the
  schema.  Here the same single-declaration invariant is
  ``utils/config.py``'s OPTIONS dict.  The rule covers both access
  styles: ``get_val("k")``/``set_val("k", ...)`` (raise at runtime only
  when the bad key is actually hit) and the raw env layer
  (``os.environ.get("CEPH_TPU_K")``), which never raises and so drifts
  silently.
* ``ceph-encoding-version-pair``: every struct that serializes through
  ``utils/encoding.py`` must keep encode and decode together (the
  ENCODE_START/DECODE_START discipline of src/include/encoding.h): an
  ``encode*`` without its ``decode*`` twin is a wire/persist format
  with no reader, and a version constant referenced on only one side is
  a compat break waiting for the next format bump.
"""

from __future__ import annotations

import ast
import functools
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import (SEV_ERROR, SEV_WARNING, FileContext,
                                    Finding, call_attr, call_name,
                                    module_str_constants, rule)

_ENV_PREFIX = "CEPH_TPU_"
_CONFIG_REL_PATH = os.path.join("ceph_tpu", "utils", "config.py")


@functools.lru_cache(maxsize=1)
def declared_options() -> Tuple[str, ...]:
    """Option names declared in utils/config.py, extracted from its AST
    (never imported: the analyzer must work on a broken tree)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cfg_path = os.path.join(root, _CONFIG_REL_PATH)
    try:
        with open(cfg_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return ()
    names: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                call_name(node) in ("_opt", "Option") and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            names.append(node.args[0].value)
    return tuple(names)


def _env_key_node(call: ast.Call) -> Optional[ast.expr]:
    name = call_name(call)
    if name in ("os.environ.get", "os.getenv", "environ.get") and call.args:
        return call.args[0]
    return None


def _literal_str(node: ast.expr, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


@rule(
    "ceph-config-undeclared-key", "ceph", SEV_ERROR,
    "config key read/written but never declared in the utils/config.py "
    "OPTIONS registry: lookups and the schema can drift apart (typo'd "
    "keys, phantom env knobs with no default, no description, no "
    "`config show`)",
)
def check_undeclared_key(ctx: FileContext) -> Iterator[Finding]:
    if ctx.path.replace("\\", "/").endswith("ceph_tpu/utils/config.py"):
        return  # the registry itself builds keys dynamically
    options: Set[str] = set(declared_options())
    if not options:
        return  # registry unreadable: stay silent rather than spam
    consts = module_str_constants(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if call_attr(node) in ("get_val", "set_val") and node.args:
                key = _literal_str(node.args[0], consts)
                if key is not None and key not in options:
                    yield ctx.finding(
                        "ceph-config-undeclared-key", node,
                        f"option {key!r} is not declared in the "
                        "utils/config.py OPTIONS registry",
                    )
                continue
            env_arg = _env_key_node(node)
            if env_arg is not None:
                key = _literal_str(env_arg, consts)
                if key and key.startswith(_ENV_PREFIX) and \
                        key[len(_ENV_PREFIX):].lower() not in options:
                    yield ctx.finding(
                        "ceph-config-undeclared-key", node,
                        f"env knob {key!r} has no `"
                        f"{key[len(_ENV_PREFIX):].lower()}` option in "
                        "the utils/config.py OPTIONS registry (the env "
                        "layer reads CEPH_TPU_<NAME>; undeclared keys "
                        "are invisible to `config show`)",
                    )
        elif isinstance(node, (ast.Subscript,)) and \
                call_name_of_sub(node) == "os.environ":
            key = _literal_str(node.slice, consts)
            if key and key.startswith(_ENV_PREFIX) and \
                    key[len(_ENV_PREFIX):].lower() not in options:
                yield ctx.finding(
                    "ceph-config-undeclared-key", node,
                    f"env knob {key!r} (subscript access) has no "
                    f"`{key[len(_ENV_PREFIX):].lower()}` option in the "
                    "utils/config.py OPTIONS registry",
                )


def call_name_of_sub(node: ast.Subscript) -> str:
    from ceph_tpu.analysis.core import dotted_name

    return dotted_name(node.value)


_VERSION_CONST = re.compile(r"^_?[A-Z][A-Z0-9_]*VERSION[A-Z0-9_]*$|"
                            r"^_?[A-Z][A-Z0-9_]*_V$")


def _referenced_version_consts(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and _VERSION_CONST.match(name):
            out.add(name)
    return out


def _pairing_findings(ctx: FileContext, scope_desc: str,
                      fns: Dict[str, ast.AST]) -> Iterator[Finding]:
    for name, fn in fns.items():
        if name.startswith("encode"):
            twin = "decode" + name[len("encode"):]
        elif name.startswith("decode"):
            twin = "encode" + name[len("decode"):]
        else:
            continue
        if twin not in fns:
            yield ctx.finding(
                "ceph-encoding-version-pair", fn,
                f"{scope_desc}{name}() has no {twin}() counterpart; "
                "serialized formats must keep both directions together "
                "(src/include/encoding.h ENCODE/DECODE discipline)",
            )
            continue
        if name.startswith("encode"):
            enc_v = _referenced_version_consts(fn)
            dec_v = _referenced_version_consts(fns[twin])
            for missing in sorted(enc_v - dec_v):
                yield ctx.finding(
                    "ceph-encoding-version-pair", fn,
                    f"{scope_desc}{name}() writes version constant "
                    f"{missing} but {twin}() never reads it: the "
                    "decoder cannot gate on struct version at the next "
                    "format bump",
                )


@rule(
    "ceph-encoding-version-pair", "ceph", SEV_WARNING,
    "encode*/decode* pairing in utils/encoding.py users: one-sided "
    "serializers and one-sided struct-version constants",
)
def check_encoding_pairs(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.imports_module("ceph_tpu.utils.encoding"):
        return
    mod_fns = {n.name: n for n in ctx.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    yield from _pairing_findings(ctx, "", mod_fns)
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            methods = {n.name: n for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            yield from _pairing_findings(
                ctx, f"{node.name}.", methods)
