"""Ceph invariant rules: the options registry and encode/decode pairing.

* ``ceph-config-undeclared-key``: the reference declares every option
  once in src/common/options.cc; readers then cannot drift from the
  schema.  Here the same single-declaration invariant is
  ``utils/config.py``'s OPTIONS dict.  The rule covers both access
  styles: ``get_val("k")``/``set_val("k", ...)`` (raise at runtime only
  when the bad key is actually hit) and the raw env layer
  (``os.environ.get("CEPH_TPU_K")``), which never raises and so drifts
  silently.
The encode/decode pairing rule moved to :mod:`rules_wire` when it grew
flow-aware (field-sequence symmetry, append-only trailing compat).
"""

from __future__ import annotations

import ast
import functools
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import (SEV_ERROR, FileContext, Finding,
                                    call_attr, call_name,
                                    module_str_constants, rule)

_ENV_PREFIX = "CEPH_TPU_"
_CONFIG_REL_PATH = os.path.join("ceph_tpu", "utils", "config.py")


@functools.lru_cache(maxsize=1)
def declared_options() -> Tuple[str, ...]:
    """Option names declared in utils/config.py, extracted from its AST
    (never imported: the analyzer must work on a broken tree)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cfg_path = os.path.join(root, _CONFIG_REL_PATH)
    try:
        with open(cfg_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return ()
    names: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                call_name(node) in ("_opt", "Option") and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            names.append(node.args[0].value)
    return tuple(names)


def _env_key_node(call: ast.Call) -> Optional[ast.expr]:
    name = call_name(call)
    if name in ("os.environ.get", "os.getenv", "environ.get") and call.args:
        return call.args[0]
    return None


def _literal_str(node: ast.expr, consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


@rule(
    "ceph-config-undeclared-key", "ceph", SEV_ERROR,
    "config key read/written but never declared in the utils/config.py "
    "OPTIONS registry: lookups and the schema can drift apart (typo'd "
    "keys, phantom env knobs with no default, no description, no "
    "`config show`)",
)
def check_undeclared_key(ctx: FileContext) -> Iterator[Finding]:
    if ctx.path.replace("\\", "/").endswith("ceph_tpu/utils/config.py"):
        return  # the registry itself builds keys dynamically
    options: Set[str] = set(declared_options())
    if not options:
        return  # registry unreadable: stay silent rather than spam
    consts = module_str_constants(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if call_attr(node) in ("get_val", "set_val") and node.args:
                key = _literal_str(node.args[0], consts)
                if key is not None and key not in options:
                    yield ctx.finding(
                        "ceph-config-undeclared-key", node,
                        f"option {key!r} is not declared in the "
                        "utils/config.py OPTIONS registry",
                    )
                continue
            env_arg = _env_key_node(node)
            if env_arg is not None:
                key = _literal_str(env_arg, consts)
                if key and key.startswith(_ENV_PREFIX) and \
                        key[len(_ENV_PREFIX):].lower() not in options:
                    yield ctx.finding(
                        "ceph-config-undeclared-key", node,
                        f"env knob {key!r} has no `"
                        f"{key[len(_ENV_PREFIX):].lower()}` option in "
                        "the utils/config.py OPTIONS registry (the env "
                        "layer reads CEPH_TPU_<NAME>; undeclared keys "
                        "are invisible to `config show`)",
                    )
        elif isinstance(node, (ast.Subscript,)) and \
                call_name_of_sub(node) == "os.environ":
            key = _literal_str(node.slice, consts)
            if key and key.startswith(_ENV_PREFIX) and \
                    key[len(_ENV_PREFIX):].lower() not in options:
                yield ctx.finding(
                    "ceph-config-undeclared-key", node,
                    f"env knob {key!r} (subscript access) has no "
                    f"`{key[len(_ENV_PREFIX):].lower()}` option in the "
                    "utils/config.py OPTIONS registry",
                )


def call_name_of_sub(node: ast.Subscript) -> str:
    from ceph_tpu.analysis.core import dotted_name

    return dotted_name(node.value)


# NOTE: the encode/decode pairing rule that used to live here
# (ceph-encoding-version-pair) grew into the flow-aware wire-schema
# pack: see rules_wire.py (wire-version-pairing carries the old
# checks; wire-schema-symmetry / wire-trailing-compat add the field
# sequence and append-only compat analysis).
