"""Runtime atomic-section verifier: the declared invariants, tested.

The static rule (``rules_interleave.check_atomic_sections``) proves no
*lexical* task-switch point sits inside a declared atomic section.
This module closes the loop at runtime so the annotation itself is
tested, not trusted: under tier-1 every event loop gets a verifying
task factory whose coroutine shim observes each yield-to-the-loop and
walks the suspended await chain's frames; a frame parked between a
section's markers means a task switch happened inside a region the
code declared switch-free -- recorded as a violation (and attributed
to the running test by the conftest hook).

Cost: one generator shim per task and, per yield, a short frame walk
with one dict probe per frame (only files that declare sections are in
the table).  No tracing/profiling hooks, so the suite's hot paths are
untouched between yields.

The FaultInjector additionally reports every injected tear
(mid-burst connection kill, apply-window primary kill) via
:func:`on_tear`; the verifier then asserts no OTHER task is suspended
inside a section at tear time -- i.e. the tear window crosses only
watermark-safe states.  Since sections are yield-free this can only
fire if the static layer was evaded (dynamic code, monkeypatching),
which is exactly the gap a runtime verifier exists to cover.
"""

from __future__ import annotations

import asyncio
import os
import types
from typing import Dict, List, Optional, Tuple

from ceph_tpu.analysis.core import parse_atomic_sections


class AtomicViolation:
    """One observed task switch inside a declared atomic section."""

    __slots__ = ("section", "path", "line", "task", "note")

    def __init__(self, section: str, path: str, line: int, task: str,
                 note: str):
        self.section = section
        self.path = path
        self.line = line
        self.task = task
        self.note = note

    def __repr__(self) -> str:
        return (f"task {self.task!r} suspended at {self.path}:{self.line} "
                f"inside atomic section {self.section!r} ({self.note})")


class AtomicSectionError(AssertionError):
    """Raised (opt-in) when a task switches inside an atomic section."""


class AtomicVerifier:
    """Section registry + the verifying coroutine shim."""

    def __init__(self, raise_on_violation: bool = False):
        #: realpath -> [(name, start, end)], sorted by start
        self.sections: Dict[str, List[Tuple[str, int, int]]] = {}
        self.violations: List[AtomicViolation] = []
        self.raise_on_violation = raise_on_violation

    # -- registration ------------------------------------------------------

    def register_source(self, path: str, source: str) -> int:
        """Register every well-formed section declared in ``source``;
        returns how many.  Malformed pairs are the static rule's
        finding, not a runtime concern -- they are skipped here."""
        sections, _problems = parse_atomic_sections(source.splitlines())
        if not sections:
            return 0
        key = os.path.realpath(path)
        table = self.sections.setdefault(key, [])
        for s in sections:
            table.append((s.name, s.start, s.end))
        table.sort(key=lambda t: t[1])
        return len(sections)

    def register_file(self, path: str) -> int:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            return 0
        if "atomic-section" not in source:
            return 0  # cheap pre-filter: most files declare nothing
        return self.register_source(path, source)

    def register_tree(self, root: str) -> int:
        total = 0
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in filenames:
                if fn.endswith(".py"):
                    total += self.register_file(os.path.join(dirpath, fn))
        return total

    # -- the check ---------------------------------------------------------

    def _hit(self, filename: str,
             lineno: int) -> Optional[Tuple[str, int, int]]:
        table = self.sections.get(filename)
        if table is None:
            table = self.sections.get(os.path.realpath(filename))
            if table is None:
                return None
            # memoize the spelling the interpreter actually uses
            self.sections[filename] = table
        for name, start, end in table:
            if start < lineno < end:
                return name, start, end
        return None

    def _record(self, section: str, path: str, line: int,
                note: str) -> None:
        task = asyncio.current_task()
        v = AtomicViolation(section, path, line,
                            task.get_name() if task else "<no task>", note)
        self.violations.append(v)
        if self.raise_on_violation:
            raise AtomicSectionError(repr(v))

    def check_awaitable(self, coro, note: str) -> None:
        """Walk a suspended coroutine's await chain; record a violation
        for every frame parked inside a registered section."""
        cur = coro
        for _ in range(64):  # chain-depth bound (cycles are impossible,
            # but a bound keeps the shim's worst case flat)
            frame = getattr(cur, "cr_frame", None)
            if frame is None:
                frame = getattr(cur, "gi_frame", None)
            if frame is None:
                return
            hit = self._hit(frame.f_code.co_filename, frame.f_lineno)
            if hit is not None:
                self._record(hit[0], frame.f_code.co_filename,
                             frame.f_lineno, note)
            nxt = getattr(cur, "cr_await", None)
            if nxt is None:
                nxt = getattr(cur, "gi_yieldfrom", None)
            if nxt is None and frame.f_code.co_name == "driven":
                # the verifying shim itself (a task's outermost frame
                # when walked from Task.get_coro() in the tear sweep):
                # bridge into the wrapped coroutine it drives
                nxt = frame.f_locals.get("coro")
            if nxt is None:
                return
            cur = nxt

    def check_all_tasks(self, note: str) -> None:
        """Tear-time sweep: no task may be parked inside a section when
        an injected fault fires (watermark-safe tear states only)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        current = asyncio.current_task(loop)
        for task in asyncio.all_tasks(loop):
            if task is current or task.done():
                continue
            coro = task.get_coro()
            if coro is not None:
                self.check_awaitable(coro, note)

    # -- the shim ----------------------------------------------------------

    def wrap(self, coro):
        """A pass-through driver for ``coro`` that inspects the await
        chain at every yield-to-the-loop."""
        if not asyncio.iscoroutine(coro):
            return coro

        @types.coroutine
        def driven():
            to_send = None
            to_throw = None
            while True:
                try:
                    if to_throw is not None:
                        yielded = coro.throw(to_throw)
                    else:
                        yielded = coro.send(to_send)
                except StopIteration as e:
                    return e.value
                # the inner coroutine is suspended at a real yield:
                # this is the only moment another task can run
                self.check_awaitable(coro, "yield observed by verifier")
                to_send = None
                to_throw = None
                try:
                    to_send = yield yielded
                except GeneratorExit:
                    coro.close()
                    raise
                except BaseException as e:  # noqa: BLE001 -- relayed
                    to_throw = e            # into the inner coroutine

        return driven()

    def install(self, loop: asyncio.AbstractEventLoop) -> None:
        verifier = self

        def factory(loop_, coro, **kwargs):
            wrapped = verifier.wrap(coro)
            task = asyncio.Task(wrapped, loop=loop_, **kwargs)
            if wrapped is not coro:
                # a task cancelled BEFORE its first step closes only
                # the shim (a not-yet-started generator's throw never
                # enters its body), which would leave the wrapped
                # coroutine un-started -> RuntimeWarning at GC.  Close
                # it explicitly once the task is done; close() on a
                # finished coroutine is a no-op.
                def _close(_task, coro=coro):
                    try:
                        coro.close()
                    except Exception:  # noqa: BLE001 -- best-effort GC
                        pass

                task.add_done_callback(_close)
            return task

        loop.set_task_factory(factory)


#: process-global verifier (the tier-1 conftest installs it); tests
#: that provoke violations on purpose build private instances instead
_GLOBAL: Optional[AtomicVerifier] = None


def global_verifier() -> Optional[AtomicVerifier]:
    return _GLOBAL


def violations() -> List[AtomicViolation]:
    return list(_GLOBAL.violations) if _GLOBAL is not None else []


def register_default_sections(verifier: AtomicVerifier) -> int:
    """Register every section declared under the ceph_tpu package and
    tools/ (the scan is one substring probe per file)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    n = verifier.register_tree(pkg_root)
    tools = os.path.join(repo_root, "tools")
    if os.path.isdir(tools):
        n += verifier.register_tree(tools)
    return n


class _VerifyingPolicy(asyncio.DefaultEventLoopPolicy):
    """Event-loop policy whose loops carry the verifying task factory
    (covers ``asyncio.run`` and ``asyncio.new_event_loop`` both)."""

    def __init__(self, verifier: AtomicVerifier):
        super().__init__()
        self._verifier = verifier

    def new_event_loop(self):
        loop = super().new_event_loop()
        self._verifier.install(loop)
        return loop


def install() -> AtomicVerifier:
    """Install the global verifier (idempotent): registers the repo's
    declared sections and routes every future event loop through the
    verifying task factory."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = AtomicVerifier()
        register_default_sections(_GLOBAL)
        asyncio.set_event_loop_policy(_VerifyingPolicy(_GLOBAL))
    return _GLOBAL


def on_tear(kind: str) -> None:
    """FaultInjector hook: an injected tear (connection kill, apply-
    window primary kill) just fired; assert no task is parked inside an
    atomic section (the tear crosses only watermark-safe states)."""
    if _GLOBAL is not None:
        _GLOBAL.check_all_tasks(f"injected tear ({kind})")
