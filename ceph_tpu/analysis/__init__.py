"""cephlint: AST-based static analysis for the ceph_tpu tree.

Reference: Ceph ships invariant-enforcement tooling alongside its data
path (lockdep, src/test static suites, CI clang analyses); this package
plays that role for the reproduction.  Three rule packs:

* **async** -- orphaned ``create_task`` results, unawaited coroutines,
  blocking calls inside ``async def``, ``await`` while holding a
  non-async lock.  The motivating bug class is the PR-1 messenger wedge:
  a dropped tick-loop task that survived shutdown and hung tier-1.
* **jax** -- host<->device syncs in the codec/coalescer hot paths,
  dtype drift away from the GF word dtype in kernel code, Python loops
  over device arrays.
* **ceph** -- config keys read but never declared in the
  ``utils/config.py`` options registry, encode/decode struct pairing in
  ``utils/encoding.py`` users.

Entry points: :func:`ceph_tpu.analysis.runner.run` (programmatic) and
``tools/cephlint.py`` (CLI).  Rules self-register on import via the
``@rule`` decorator in :mod:`ceph_tpu.analysis.core`.
"""

from ceph_tpu.analysis.core import Finding, Rule, all_rules, rule  # noqa: F401
from ceph_tpu.analysis.runner import run, run_paths  # noqa: F401
