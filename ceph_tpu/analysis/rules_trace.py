"""Observability-hygiene rules.

``trace-span-unfinished``: a started span / TrackedOp with a CFG path
that never reaches ``finish()``.  The round-16 trace subsystem keeps a
live-span map exactly because an unfinished span is silent loss twice
over -- the op never lands in the collector (its trace is a hole) and
the live map grows until the overflow counter starts churning.  The
runtime counterpart (``trace.unfinished_count()``, gated by the
ci_lint traced-op smoke) only sees leaks a workload happens to drive;
this rule walks every function's control-flow graph
(``analysis/cfg.py``) and flags creation sites where SOME path falls
off the function without crossing a ``finish()`` call or a ``with``
block on the span.

Ownership transfer is respected: a span that escapes the function
(returned, yielded, passed to another call, stored into state or a
container, aliased) is the receiver's to finish -- the optracker's
``create_request(span=...)`` hand-off and the OSD's tracked-op
plumbing are exactly this shape.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from ceph_tpu.analysis import cfg as cfg_mod
from ceph_tpu.analysis.core import (SEV_WARNING, FileContext, Finding,
                                    call_attr, call_name, rule)

#: call attrs that mint a span/TrackedOp the caller must close.  A bare
#: ``child()`` is excluded: too generic an attr name to match without
#: type inference (child spans ride ``with`` blocks in practice).
_SPAN_CREATORS = {"new_trace", "batch_span"}
_TRACKER_CREATOR = "create_request"


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Every AST node of ``fn``'s own body, nested defs excluded (their
    spans have their own CFG and their own rule pass)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_creator(call: ast.Call) -> bool:
    attr = call_attr(call)
    if attr in _SPAN_CREATORS:
        return True
    if attr == _TRACKER_CREATOR:
        # require a tracker-ish receiver so unrelated create_request
        # APIs (none in-tree today) cannot false-positive
        return "tracker" in call_name(call).lower()
    return False


def _escapes(ctx: FileContext, fn: ast.AST, var: str,
             creation: ast.Call) -> bool:
    """True when ``var`` leaves the function's hands: returned, passed,
    stored, aliased, or placed in a container -- ownership (and the
    finish obligation) moved with it."""
    parents = ctx.parent_map()
    for node in _own_nodes(fn):
        if not (isinstance(node, ast.Name) and node.id == var and
                isinstance(node.ctx, ast.Load)):
            continue
        parent = parents.get(node)
        if isinstance(parent, ast.Attribute):
            continue  # x.method()/x.attr: plain use
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(parent, ast.Call):
            return True  # positional arg (x.m() parents as Attribute)
        if isinstance(parent, ast.keyword):
            return True
        if isinstance(parent, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign, ast.NamedExpr)):
            return True  # aliased or stored somewhere
        if isinstance(parent, (ast.List, ast.Tuple, ast.Set, ast.Dict,
                               ast.Starred)):
            return True
        if isinstance(parent, ast.withitem):
            continue  # `with x:` is the cleanup idiom, handled below
        if isinstance(parent, (ast.Compare, ast.BoolOp, ast.UnaryOp,
                               ast.IfExp, ast.If, ast.While,
                               ast.FormattedValue, ast.Expr,
                               ast.Subscript, ast.Await, ast.Assert)):
            continue  # truthiness / formatting / indexing: plain use
        return True  # unknown context: assume a hand-off (no false
        #              positives from contexts this walk cannot judge)
    return False


def _header_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Expressions evaluated BY this CFG node itself: a compound
    statement's nested blocks are separate CFG nodes, so a finish()
    buried in one branch must not make the whole If a closer."""
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt, ast.ExceptHandler)):
            continue
        for node in ast.walk(child):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                break
            yield node


def _closing_stmts(cfg: "cfg_mod.CFG", var: str) -> Set[ast.stmt]:
    """Statements that discharge the finish obligation: a ``finish()``
    call on ``var``, or a ``with var`` block (``__exit__`` finishes)."""
    out: Set[ast.stmt] = set()
    for stmt in cfg.stmts:
        if isinstance(stmt, (ast.With, ast.AsyncWith)) and any(
            isinstance(item.context_expr, ast.Name) and
            item.context_expr.id == var
            for item in stmt.items
        ):
            out.add(stmt)
            continue
        for node in _header_exprs(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "finish" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == var:
                out.add(stmt)
                break
    return out


def _leaks(cfg: "cfg_mod.CFG", creation: ast.stmt,
           closers: Set[ast.stmt]) -> bool:
    """True when some path creation -> ... -> EXIT crosses no closer."""
    seen: Set[int] = set()
    frontier: List[object] = list(cfg.succ.get(creation, []))
    while frontier:
        node = frontier.pop()
        if node is cfg_mod.EXIT:
            return True
        if id(node) in seen or node in closers:
            continue
        seen.add(id(node))
        frontier.extend(cfg.succ.get(node, []))
    return False


@rule(
    "trace-span-unfinished", "ceph", SEV_WARNING,
    "a span/TrackedOp minted by new_trace()/batch_span()/"
    "create_request() has a control-flow path that exits the function "
    "without finish() (or a `with` block): the op never reaches the "
    "collector and the live-span map leaks -- finish in a try/finally, "
    "use the span as a context manager, or hand ownership off "
    "explicitly (return/store/pass it)",
)
def check_span_unfinished(ctx: FileContext) -> Iterator[Finding]:
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        creations = []
        for stmt in _own_nodes(fn):
            if isinstance(stmt, ast.Assign) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    isinstance(stmt.value, ast.Call) and \
                    _is_creator(stmt.value):
                creations.append((stmt, stmt.targets[0].id))
        if not creations:
            continue
        graph: Optional[cfg_mod.CFG] = None
        closers_by_var: Dict[str, Set[ast.stmt]] = {}
        for stmt, var in creations:
            if _escapes(ctx, fn, var, stmt.value):
                continue
            if graph is None:
                graph = cfg_mod.build(fn)
            closers = closers_by_var.get(var)
            if closers is None:
                closers = closers_by_var[var] = _closing_stmts(
                    graph, var)
            if stmt in closers:
                # `x = creator(); x.finish()` folded into one statement
                # cannot happen for an Assign, but a closer that IS the
                # creation would wrongly discharge itself
                closers = closers - {stmt}
            if _leaks(graph, stmt, closers):
                yield ctx.finding(
                    "trace-span-unfinished", stmt,
                    f"span '{var}' from {call_name(stmt.value)}() can "
                    "reach function exit without finish(): the trace "
                    "loses the op and the live-span map leaks; close "
                    "it in a try/finally or a `with` block (escaping "
                    "spans -- returned/stored/passed -- are exempt)",
                )
