"""Wire-schema symmetry rules.

The encoding framework (``utils/encoding.py``) gives every wire and
persist struct the same linear shape: an ordered sequence of codec
calls (``varint``/``string``/``blob``/``value``/...), optionally
version-guarded at the tail.  PRs 3 and 5 both evolved that shape under
compat constraints -- the v4 messenger's TRAILING piggyback-ack field
that v3 receivers never read, and the pre-reqid-frame rule where
``ECSubWrite.reqid`` decodes as ``dec.value() if dec.remaining() else
None`` -- and both rules lived only in review comments.  These rules
parse paired ``encode*``/``decode*`` bodies (and the
``message_encoder``/``decode_message`` dispatcher branches in
``msg/wire.py``, matched by their shared ``_MSG_*`` discriminator
constants) into linear field sequences and machine-check:

* ``wire-schema-symmetry`` -- encoder and decoder read/write the same
  ops in the same order (loops compared structurally; ``blob_ref``/
  ``blob_parts`` are wire-equal to ``blob``);
* ``wire-trailing-compat`` -- optional fields (``dec.remaining()`` /
  version-const guards) form a SUFFIX: appending is the only compatible
  evolution, so an unguarded field after a guarded one mis-parses every
  frame from a sender that omitted the optional field.  The guard
  itself is a contract older peers rely on, so it can be DECLARED: a
  ``# cephlint: wire-optional`` comment asserts the next decode read
  must stay guarded -- deleting the guard (the "simplifying" refactor
  that would silently break every pre-field sender) is then flagged
  even though the resulting code is internally consistent;
* ``wire-version-pairing`` -- every ``encode*`` has its ``decode*``
  twin in the same scope and no struct-version constant is referenced
  on only one side (the ENCODE_START/DECODE_START discipline; replaces
  the shallow ``ceph-encoding-version-pair`` rule).

Pure AST, like every cephlint rule: branches whose field content cannot
be linearized (a non-guard ``if`` writing fields) make the sequence
opaque from that point on rather than guessing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis.core import (SEV_ERROR, SEV_WARNING, FileContext,
                                    Finding, dotted_name, rule)

#: codec methods that produce/consume one wire field, normalized to the
#: wire-identical op (blob_ref and blob_parts emit a blob's bytes)
_FIELD_OPS = {
    "u8": "u8", "u32": "u32", "u64": "u64", "varint": "varint",
    "blob": "blob", "blob_ref": "blob", "blob_parts": "blob",
    "string": "string", "value": "value",
}
#: codec methods that are not fields (terminals, cursor queries)
_NON_FIELD_OPS = {"bytes", "parts", "nbytes", "remaining", "_take"}

_VERSION_CONST = re.compile(r"^_?[A-Z][A-Z0-9_]*VERSION[A-Z0-9_]*$|"
                            r"^_?[A-Z][A-Z0-9_]*_V$")

#: declared-optional marker: the next decode field read after this
#: comment must be remaining()/version guarded (older peers omit it)
_WIRE_OPTIONAL = re.compile(r"#\s*cephlint:\s*wire-optional\b")


class Item:
    """One linearized wire field / helper call."""

    __slots__ = ("kind", "name", "depth", "guarded", "node", "arg")

    def __init__(self, kind: str, name: str, depth: int, guarded: bool,
                 node: ast.AST, arg: Optional[str] = None):
        self.kind = kind      # "f" field | "c" helper call | "opaque"
        self.name = name
        self.depth = depth    # loop nesting depth
        self.guarded = guarded
        self.node = node
        self.arg = arg        # u8 discriminator constant, when a Name

    def describe(self) -> str:
        if self.kind == "c":
            return f"call {self.name}()"
        label = f"{self.name}"
        if self.depth:
            label += f" (in loop x{self.depth})"
        if self.guarded:
            label += " [guarded]"
        return label


def _norm_helper(name: str) -> str:
    return name.replace("encode", "", 1) if "encode" in name \
        else name.replace("decode", "", 1)


class _Extractor:
    """Linearize one function body's codec traffic on variable ``var``."""

    def __init__(self, var: str, kind: str):
        self.var = var
        #: "encode" | "decode": Encoder methods return self, so chained
        #: calls stay "the codec object"; Decoder methods return VALUES
        #: (``dec.value().items()`` is a dict method, not a codec op)
        self.kind = kind
        self.items: List[Item] = []
        self._depth = 0
        self._guard = 0

    # -- emit ---------------------------------------------------------------

    def _emit(self, kind: str, name: str, node: ast.AST,
              arg: Optional[str] = None) -> None:
        self.items.append(Item(kind, name, self._depth,
                               self._guard > 0, node, arg))

    # -- classification -----------------------------------------------------

    def _is_chain(self, expr: ast.AST) -> bool:
        """``expr`` evaluates to the codec object: the var itself or a
        chained codec call on it (Encoder methods return self)."""
        if isinstance(expr, ast.Name):
            return expr.id == self.var
        if self.kind == "encode" and isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute):
            return self._is_chain(expr.func.value)
        return False

    def _guard_test(self, test: ast.AST) -> bool:
        """A version/compat guard: consults ``remaining()`` on the codec
        var or references a struct-version constant/name."""
        for node in ast.walk(test):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "remaining" and \
                    self._is_chain(node.func.value):
                return True
            name = None
            if isinstance(node, ast.Name):
                name = node.id
            elif isinstance(node, ast.Attribute):
                name = node.attr
            if name and (_VERSION_CONST.match(name) or
                         "version" in name.lower() or
                         name.lower() in ("struct_v", "v")):
                return True
        return False

    # -- the walk (evaluation order) ----------------------------------------

    def stmts(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.If):
            if self._guard_test(stmt.test):
                self._guard += 1
                self.stmts(stmt.body)
                self._guard -= 1
                self.stmts(stmt.orelse)
            else:
                before = len(self.items)
                self.expr(stmt.test)
                self.stmts(stmt.body)
                self.stmts(stmt.orelse)
                if len(self.items) > before:
                    # field traffic under a non-guard branch cannot be
                    # linearized: make the tail opaque instead of lying
                    del self.items[before:]
                    self._emit("opaque", "branch", stmt)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.expr(stmt.iter)
            self._depth += 1
            self.stmts(stmt.body)
            self._depth -= 1
            self.stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            before = len(self.items)
            self.expr(stmt.test)
            had_test = len(self.items) > before
            self._depth += 1
            self.stmts(stmt.body)
            self._depth -= 1
            if had_test:
                # a count read inside the while test re-runs per pass:
                # not a linear field sequence
                del self.items[before:]
                self._emit("opaque", "while", stmt)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.expr(item.context_expr)
            self.stmts(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.stmts(stmt.body)
            for handler in stmt.handlers:
                self.stmts(handler.body)
            self.stmts(stmt.orelse)
            self.stmts(stmt.finalbody)
            return
        for child in ast.iter_child_nodes(stmt):
            if not isinstance(child, ast.stmt):
                self.expr(child)

    def expr(self, node: ast.AST) -> None:
        if node is None or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.IfExp):
            if self._guard_test(node.test):
                self._guard += 1
                self.expr(node.body)
                self._guard -= 1
                self.expr(node.orelse)
            else:
                before = len(self.items)
                self.expr(node.test)
                self.expr(node.body)
                self.expr(node.orelse)
                if len(self.items) > before:
                    del self.items[before:]
                    self._emit("opaque", "ifexp", node)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                self.expr(gen.iter)
            self._depth += 1
            if isinstance(node, ast.DictComp):
                self.expr(node.key)
                self.expr(node.value)
            else:
                self.expr(node.elt)
            self._depth -= 1
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child)

    def _call(self, call: ast.Call) -> None:
        func = call.func
        # codec op (possibly chained): inner chain evaluates first
        if isinstance(func, ast.Attribute) and self._is_chain(func.value):
            self.expr(func.value)
            for arg in call.args:
                self.expr(arg)
            for kw in call.keywords:
                self.expr(kw.value)
            attr = func.attr
            if attr in _FIELD_OPS:
                arg_name = None
                if attr == "u8" and call.args and \
                        isinstance(call.args[0], ast.Name):
                    arg_name = call.args[0].id
                self._emit("f", _FIELD_OPS[attr], call, arg_name)
            elif attr not in _NON_FIELD_OPS:
                self._emit("f", attr, call)  # future op: still compared
            return
        # helper call taking the codec var: one nested struct
        takes_var = any(isinstance(a, ast.Name) and a.id == self.var
                        for a in call.args)
        tail = dotted_name(func).rsplit(".", 1)[-1]
        if takes_var and ("encode" in tail or "decode" in tail):
            for arg in call.args:
                if not (isinstance(arg, ast.Name) and arg.id == self.var):
                    self.expr(arg)
            self._emit("c", _norm_helper(tail), call)
            return
        self.expr(func)
        for arg in call.args:
            self.expr(arg)
        for kw in call.keywords:
            self.expr(kw.value)


def _codec_var(fn: ast.AST, kind: str) -> Optional[str]:
    """The Encoder/Decoder variable a function works on: a parameter
    named ``enc*``/``dec*``, or a local assigned from ``Encoder()`` /
    ``Decoder(...)``."""
    prefix = "enc" if kind == "encode" else "dec"
    for arg in fn.args.args:
        if arg.arg == prefix or arg.arg.startswith(prefix):
            return arg.arg
    ctor = "Encoder" if kind == "encode" else "Decoder"
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                dotted_name(node.value.func).rsplit(".", 1)[-1] == ctor:
            return node.targets[0].id
    return None


def _extract(fn: ast.AST, kind: str,
             body: Optional[List[ast.stmt]] = None,
             var: Optional[str] = None) -> Optional[List[Item]]:
    var = var or _codec_var(fn, kind)
    if var is None:
        return None
    ex = _Extractor(var, kind)
    ex.stmts(body if body is not None else fn.body)
    return ex.items


def _truncate_opaque(items: List[Item]) -> Tuple[List[Item], bool]:
    for i, item in enumerate(items):
        if item.kind == "opaque":
            return items[:i], True
    return items, False


def _scope_functions(ctx: FileContext):
    """(scope description, {name: def node}) for module + each class."""
    mod = {n.name: n for n in ctx.tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    yield "", mod
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            yield f"{node.name}.", {
                n.name: n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _pairs(fns: Dict[str, ast.AST]):
    for name, fn in fns.items():
        if name.startswith("encode") and \
                ("decode" + name[len("encode"):]) in fns:
            yield name, fn, fns["decode" + name[len("encode"):]]


def _referenced_version_consts(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and _VERSION_CONST.match(name):
            out.add(name)
    return out


# -- dispatcher branches (msg/wire.py message_encoder/decode_message) ------

def _encoder_branches(ctx: FileContext) -> Dict[str, Tuple[List[Item],
                                                           ast.AST]]:
    """isinstance-dispatched encoder branches keyed by the ``_MSG_*``
    discriminator each branch stamps with ``enc.u8(CONST)``."""
    out: Dict[str, Tuple[List[Item], ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (isinstance(test, ast.Call) and
                dotted_name(test.func) == "isinstance"):
            continue
        fn = _enclosing_fn(ctx, node)
        if fn is None:
            continue
        var = _codec_var(fn, "encode")
        if var is None:
            continue
        items = _extract(fn, "encode", body=node.body, var=var)
        if items and items[0].kind == "f" and items[0].name == "u8" and \
                items[0].arg:
            out[items[0].arg] = (items[1:], node)
    return out


def _decoder_branches(ctx: FileContext, keys: Set[str]
                      ) -> Dict[str, Tuple[List[Item], ast.AST]]:
    """``if kind == _MSG_X:`` decoder branches for known discriminators."""
    out: Dict[str, Tuple[List[Item], ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1 and
                isinstance(test.ops[0], ast.Eq) and
                isinstance(test.comparators[0], ast.Name) and
                test.comparators[0].id in keys):
            continue
        fn = _enclosing_fn(ctx, node)
        if fn is None:
            continue
        var = _codec_var(fn, "decode")
        if var is None:
            continue
        items = _extract(fn, "decode", body=node.body, var=var)
        if items is not None:
            out[test.comparators[0].id] = (items, node)
    return out


def _enclosing_fn(ctx: FileContext, node: ast.AST) -> Optional[ast.AST]:
    parents = ctx.parent_map()
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
    return None


# -- rules -----------------------------------------------------------------

def _compare(ctx: FileContext, what: str, enc_items: List[Item],
             dec_items: List[Item],
             anchor: ast.AST) -> Iterator[Finding]:
    enc_seq, _enc_bail = _truncate_opaque(enc_items)
    dec_seq, _dec_bail = _truncate_opaque(dec_items)
    limit = min(len(enc_seq), len(dec_seq))
    for i in range(limit):
        a, b = enc_seq[i], dec_seq[i]
        if (a.kind, a.name if a.kind == "c" else a.name, a.depth) != \
                (b.kind, b.name if b.kind == "c" else b.name, b.depth):
            yield ctx.finding(
                "wire-schema-symmetry", b.node,
                f"{what}: field #{i + 1} diverges -- encoder writes "
                f"{a.describe()} (line {a.node.lineno}) but decoder "
                f"reads {b.describe()}; one side reordered or retyped "
                "a field and every frame now mis-parses from that "
                "offset",
            )
            return
    if _enc_bail or _dec_bail:
        return  # opaque tail: cannot judge the remainder
    if len(enc_seq) != len(dec_seq):
        if len(enc_seq) > len(dec_seq):
            extra, side, node = enc_seq[len(dec_seq)], "encoder", \
                enc_seq[len(dec_seq)].node
            other = "decoder never reads it"
        else:
            extra, side, node = dec_seq[len(enc_seq)], "decoder", \
                dec_seq[len(enc_seq)].node
            other = "encoder never writes it"
        yield ctx.finding(
            "wire-schema-symmetry", node,
            f"{what}: {side} has trailing {extra.describe()} that the "
            f"{other}; unguarded length skew breaks every peer on the "
            "other side of the wire",
        )


@rule(
    "wire-schema-symmetry", "ceph", SEV_ERROR,
    "paired encode*/decode* bodies (and the msg/wire.py dispatcher "
    "branches, matched by _MSG_* discriminator) linearized into field "
    "sequences must agree op-for-op, in order, loop structure included "
    "-- a reordered/retyped/one-sided field mis-parses every frame from "
    "that offset on",
)
def check_schema_symmetry(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.imports_module("ceph_tpu.utils.encoding"):
        return
    for scope, fns in _scope_functions(ctx):
        for name, enc_fn, dec_fn in _pairs(fns):
            enc_items = _extract(enc_fn, "encode")
            dec_items = _extract(dec_fn, "decode")
            if enc_items is None or dec_items is None:
                continue
            # decode-side guards are the compat tail: compare content
            yield from _compare(
                ctx, f"{scope}{name}/decode{name[len('encode'):]}",
                enc_items, dec_items, dec_fn)
    enc_branches = _encoder_branches(ctx)
    if enc_branches:
        dec_branches = _decoder_branches(ctx, set(enc_branches))
        for key in sorted(set(enc_branches) & set(dec_branches)):
            enc_items, _ = enc_branches[key]
            dec_items, dnode = dec_branches[key]
            yield from _compare(ctx, f"message kind {key}", enc_items,
                                dec_items, dnode)


@rule(
    "wire-trailing-compat", "ceph", SEV_ERROR,
    "optional wire fields (dec.remaining() / version-const guards) must "
    "form a SUFFIX of the struct: append-only evolution is the only "
    "compatible one (the v3->v4 messenger and pre-reqid ECSubWrite "
    "rules) -- an unguarded field after a guarded one mis-parses every "
    "frame from an older sender; a `# cephlint: wire-optional` comment "
    "declares the next decode read guard-mandatory, so removing the "
    "guard is flagged even when both sides still agree",
)
def check_trailing_compat(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.imports_module("ceph_tpu.utils.encoding"):
        return

    # honor only REAL comment tokens: a `wire-optional` spelling quoted
    # inside a docstring/fixture string is prose, not a declaration
    # (the round-12 section-marker gotcha, same fix)
    from ceph_tpu.analysis.core import _comment_line_numbers

    comment_lines = _comment_line_numbers(ctx.lines)
    opt_lines = [i for i, line in enumerate(ctx.lines, start=1)
                 if _WIRE_OPTIONAL.search(line)
                 and (comment_lines is None or i in comment_lines)]

    def suffix_check(items: Optional[List[Item]], what: str
                     ) -> Iterator[Finding]:
        if not items:
            return
        seq, _ = _truncate_opaque(items)
        seen_guard: Optional[Item] = None
        for item in seq:
            if item.guarded:
                seen_guard = item
            elif seen_guard is not None:
                yield ctx.finding(
                    "wire-trailing-compat", item.node,
                    f"{what}: {item.describe()} is unguarded but "
                    f"follows optional {seen_guard.describe()} (line "
                    f"{seen_guard.node.lineno}); when the optional "
                    "field is absent this read consumes the wrong "
                    "bytes -- optional fields must be the trailing "
                    "suffix",
                )
                return

    def declared_check(items: Optional[List[Item]], span: ast.AST,
                       what: str) -> Iterator[Finding]:
        """`# cephlint: wire-optional` inside ``span``: the next decode
        field read must carry a remaining()/version guard.  The
        declaration survives the refactor that deletes the guard (the
        comment stays behind), which is exactly when it must fire."""
        if not items:
            return
        end = getattr(span, "end_lineno", None) or (1 << 30)
        for ln in opt_lines:
            if not span.lineno <= ln <= end:
                continue
            nxt = next((it for it in items
                        if it.kind == "f" and it.node.lineno >= ln), None)
            if nxt is not None and not nxt.guarded:
                yield ctx.finding(
                    "wire-trailing-compat", nxt.node,
                    f"{what}: {nxt.describe()} is declared wire-optional "
                    f"(line {ln}) but read unconditionally; peers that "
                    "predate the field send frames without it, so the "
                    "read must stay behind dec.remaining() or a "
                    "version guard",
                )

    for scope, fns in _scope_functions(ctx):
        for name, fn in fns.items():
            if name.startswith("encode"):
                yield from suffix_check(
                    _extract(fn, "encode"), f"{scope}{name}")
            elif name.startswith("decode"):
                yield from suffix_check(
                    _extract(fn, "decode"), f"{scope}{name}")
    if opt_lines:
        # declarations anchor to their INNERMOST enclosing function
        # (any name -- the tcp.py frame parser is not a decode* twin),
        # decoded with that function's own codec var
        fns_all = [n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        seen: Set[int] = set()
        for ln in opt_lines:
            best = None
            for fn in fns_all:
                fend = getattr(fn, "end_lineno", None) or fn.lineno
                if fn.lineno <= ln <= fend and \
                        (best is None or fn.lineno > best.lineno):
                    best = fn
            if best is None or id(best) in seen:
                continue
            seen.add(id(best))
            yield from declared_check(
                _extract(best, "decode"), best, best.name)
    enc_branches = _encoder_branches(ctx)
    if enc_branches:
        for key, (items, node) in sorted(_decoder_branches(
                ctx, set(enc_branches)).items()):
            yield from suffix_check(items, f"message kind {key}")
            yield from declared_check(items, node, f"message kind {key}")


@rule(
    "wire-version-pairing", "ceph", SEV_WARNING,
    "encode*/decode* twins in utils/encoding.py users: a one-sided "
    "serializer is a wire format with no reader, and a struct-version "
    "constant referenced only by the encoder cannot be gated on at the "
    "next format bump (ENCODE_START/DECODE_START discipline)",
)
def check_version_pairing(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.imports_module("ceph_tpu.utils.encoding"):
        return
    for scope, fns in _scope_functions(ctx):
        for name, fn in fns.items():
            if name.startswith("encode"):
                twin = "decode" + name[len("encode"):]
            elif name.startswith("decode"):
                twin = "encode" + name[len("decode"):]
            else:
                continue
            if twin not in fns:
                yield ctx.finding(
                    "wire-version-pairing", fn,
                    f"{scope}{name}() has no {twin}() counterpart; "
                    "serialized formats must keep both directions "
                    "together (src/include/encoding.h ENCODE/DECODE "
                    "discipline)",
                )
                continue
            if name.startswith("encode"):
                enc_v = _referenced_version_consts(fn)
                dec_v = _referenced_version_consts(fns[twin])
                for missing in sorted(enc_v - dec_v):
                    yield ctx.finding(
                        "wire-version-pairing", fn,
                        f"{scope}{name}() writes version constant "
                        f"{missing} but {twin}() never reads it: the "
                        "decoder cannot gate on struct version at the "
                        "next format bump",
                    )
