"""Statement-level control-flow graphs for the flow-aware rules.

One CFG per function definition.  Nodes are the function's own
``ast.stmt`` objects (compound headers -- ``if``/``while``/``for``/
``try``/``with`` -- are nodes carrying their test/iter/items
expressions; their block bodies are separate nodes).  Edges follow the
usual approximations:

* loops get a body edge, a fall-through edge (taken even for
  ``while True`` only when the test is non-constant) and a back edge;
* every statement inside a ``try`` body may raise into each handler
  (call-free statements too -- the cheap over-approximation);
* ``return`` goes to EXIT -- through the enclosing ``finally`` block
  first when there is one (the finalbody runs on the way out, so a
  cleanup statement there IS on every return path); ``raise`` goes to
  the innermost handlers (or EXIT), ``break``/``continue`` to their
  loop targets.

The rules ask one kind of question: *can execution flow from statement
A to statement B, and does some such path cross a task-switch point?*
:meth:`CFG.crosses_yield` answers it with a BFS over ``(node,
crossed)`` states, where the yield set comes from the call graph's
may-await classification -- so an ``await self._pure_helper()`` on the
path does not count as an interleaving window but an
``await self._helper_that_drains()`` does.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: graph sink: returns, final statements, uncaught raises
EXIT = "<exit>"


class CFG:
    """Control-flow graph over one function's statements."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.succ: Dict[object, List[object]] = {}
        self.stmts: List[ast.stmt] = []
        self._stmt_set: Set[int] = set()
        entry = self._block(fn.body, [EXIT], [], [], [EXIT], [EXIT])
        self.entry: List[object] = entry

    # -- construction ------------------------------------------------------

    def _add(self, node: ast.stmt) -> None:
        if id(node) not in self._stmt_set:
            self._stmt_set.add(id(node))
            self.stmts.append(node)
            self.succ.setdefault(node, [])

    def _edge(self, src: ast.stmt, dsts: Iterable[object]) -> None:
        out = self.succ.setdefault(src, [])
        for d in dsts:
            if all(d is not e for e in out):
                out.append(d)

    def _block(self, stmts: Sequence[ast.stmt], follow: List[object],
               breaks: List[object], continues: List[object],
               raises: List[object],
               returns: List[object]) -> List[object]:
        """Wire a statement list; returns the block's entry points.
        ``returns`` is where a ``return`` statement flows: EXIT
        normally, the enclosing ``finally`` block's entry inside a
        try/finally (the finalbody runs before the function leaves)."""
        if not stmts:
            return list(follow)
        entries: Optional[List[object]] = None
        # wire back-to-front so each statement knows its successor entry
        nxt: List[object] = list(follow)
        for stmt in reversed(stmts):
            nxt = self._stmt(stmt, nxt, breaks, continues, raises, returns)
        entries = nxt
        return entries

    def _stmt(self, stmt: ast.stmt, follow: List[object],
              breaks: List[object], continues: List[object],
              raises: List[object], returns: List[object]) -> List[object]:
        """Wire one statement; returns its entry points (usually just
        ``[stmt]``)."""
        self._add(stmt)
        if isinstance(stmt, ast.If):
            body = self._block(stmt.body, follow, breaks, continues, raises,
                               returns)
            orelse = self._block(stmt.orelse, follow, breaks, continues,
                                 raises, returns) \
                if stmt.orelse else list(follow)
            self._edge(stmt, body)
            self._edge(stmt, orelse)
        elif isinstance(stmt, (ast.While,)):
            body = self._block(stmt.body, [stmt], follow, [stmt], raises,
                               returns)
            self._edge(stmt, body)
            test = stmt.test
            infinite = isinstance(test, ast.Constant) and bool(test.value)
            if not infinite or stmt.orelse:
                self._edge(stmt, self._block(
                    stmt.orelse, follow, breaks, continues, raises, returns)
                    if stmt.orelse else follow)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            body = self._block(stmt.body, [stmt], follow, [stmt], raises,
                               returns)
            self._edge(stmt, body)
            self._edge(stmt, self._block(
                stmt.orelse, follow, breaks, continues, raises, returns)
                if stmt.orelse else follow)
        elif isinstance(stmt, ast.Try):
            handler_entries: List[object] = []
            final_entry = self._block(
                stmt.finalbody, follow, breaks, continues, raises, returns) \
                if stmt.finalbody else list(follow)
            # a `return` under this try runs the finalbody on the way
            # out, so it routes through final_entry, not straight to
            # EXIT (over-approximated: the finalbody's fall-through
            # edge to `follow` survives, which is the safe direction
            # for every may-reach query)
            inner_returns = final_entry if stmt.finalbody else returns
            for handler in stmt.handlers:
                handler_entries.extend(self._block(
                    handler.body, final_entry, breaks, continues, raises,
                    inner_returns))
            inner_raises = handler_entries or final_entry or list(raises)
            after_body = self._block(
                stmt.orelse, final_entry, breaks, continues, raises,
                inner_returns) \
                if stmt.orelse else final_entry
            body = self._block(stmt.body, after_body, breaks, continues,
                               inner_raises, inner_returns)
            self._edge(stmt, body)
            # any body statement may raise into the handlers
            for inner in self._own_stmts(stmt.body):
                self._edge(inner, inner_raises)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            body = self._block(stmt.body, follow, breaks, continues, raises,
                               returns)
            self._edge(stmt, body)
        elif isinstance(stmt, ast.Return):
            self._edge(stmt, returns or [EXIT])
        elif isinstance(stmt, ast.Raise):
            self._edge(stmt, raises or [EXIT])
        elif isinstance(stmt, ast.Break):
            self._edge(stmt, breaks or [EXIT])
        elif isinstance(stmt, ast.Continue):
            self._edge(stmt, continues or [EXIT])
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self._edge(stmt, follow)  # a def is one opaque statement
        else:
            self._edge(stmt, follow)
        return [stmt]

    def _own_stmts(self, stmts: Sequence[ast.stmt]) -> List[ast.stmt]:
        """All statements nested under ``stmts`` (this function's only;
        nested defs are opaque)."""
        out: List[ast.stmt] = []
        stack = list(stmts)
        while stack:
            node = stack.pop()
            out.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                stack.extend(getattr(node, field, []) or [])
            for handler in getattr(node, "handlers", []) or []:
                stack.extend(handler.body)
        return out

    # -- queries -----------------------------------------------------------

    def stmt_of(self, node: ast.AST,
                parents: Dict[ast.AST, ast.AST]) -> Optional[ast.stmt]:
        """The CFG statement whose evaluation contains ``node``."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if id(cur) in self._stmt_set:
                return cur  # type: ignore[return-value]
            cur = parents.get(cur)
        return None

    def crosses_yield(self, src: ast.stmt, dst: ast.stmt,
                      yields: Set[ast.stmt],
                      start_crossed: bool = False) -> bool:
        """True when some path src -> ... -> dst crosses a statement in
        ``yields`` strictly between the two (or ``start_crossed``,
        i.e. the yield already happened inside ``src`` itself).

        Paths re-entering ``src`` are NOT followed: once the read/guard
        statement re-executes (a loop back edge), the value is fresh
        and the original stale-read window is gone."""
        seen: Set[Tuple[int, bool]] = set()
        frontier: List[Tuple[object, bool]] = [
            (n, start_crossed) for n in self.succ.get(src, [])
        ]
        while frontier:
            node, crossed = frontier.pop()
            if node is EXIT or node is src:
                continue
            if node is dst and crossed:
                return True
            key = (id(node), crossed)
            if key in seen:
                continue
            seen.add(key)
            nxt = crossed or (node in yields and node is not dst)
            for succ in self.succ.get(node, []):
                frontier.append((succ, nxt))
        return False

    def reaches_clean(self, src: ast.stmt, dst: ast.stmt,
                      yields: Set[ast.stmt]) -> bool:
        """True when some path src -> ... -> dst crosses NO task-switch
        point: a guard with a clean path to a write is a FRESH check --
        the re-check-after-await discipline that fixes check-then-act."""
        seen: Set[int] = set()
        frontier: List[object] = list(self.succ.get(src, []))
        while frontier:
            node = frontier.pop()
            if node is dst:
                return True
            if node is EXIT or id(node) in seen or node in yields:
                continue
            seen.add(id(node))
            frontier.extend(self.succ.get(node, []))
        return False

    def first_yield_before(self, src: ast.stmt, stops: Set[ast.stmt],
                           yields: Set[ast.stmt]) -> Optional[ast.stmt]:
        """First statement in ``yields`` reachable from ``src`` without
        passing through a statement in ``stops`` (release points); None
        when every path hits a stop (or EXIT) first."""
        seen: Set[int] = set()
        frontier: List[object] = list(self.succ.get(src, []))
        while frontier:
            node = frontier.pop()
            if node is EXIT or id(node) in seen:
                continue
            seen.add(id(node))
            if node in stops:
                continue
            if node in yields:
                return node  # type: ignore[return-value]
            frontier.extend(self.succ.get(node, []))
        return None


def build(fn: ast.AST) -> CFG:
    return CFG(fn)
