"""Inline suppressions: ``# cephlint: disable=<rule>[,<rule>...]``.

Two spellings, mirroring the common linter convention (rule name goes
right after the ``=``):

* same-line: append ``# cephlint: disable=`` + the rule name to the
  flagged line, e.g. to excuse one deliberate blocking call;
* next-line: put ``# cephlint: disable-next-line=`` + the rule name on
  the line above the finding.

``disable=all`` suppresses every rule on that line.  Suppressions are
deliberately line-scoped (no file/block scope): a suppression should sit
next to the code it excuses, where review sees both together.  The
baseline file is the mechanism for bulk legacy acceptance.

Native (.c/.cpp) sources use their own comment syntax, so the marker
also matches after ``//`` or inside ``/* ... */`` (the rule-name
character class naturally excludes the closing ``*/``).
"""

from __future__ import annotations

import re
from typing import Dict, List, Set

_RE = re.compile(
    r"(?:#|//|/\*)\s*cephlint:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map of 1-based line number -> set of suppressed rule names
    (``{"all"}`` for disable=all) effective on that line."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        target = i + 1 if m.group(1) == "disable-next-line" else i
        out.setdefault(target, set()).update(rules)
    return out


def is_suppressed(suppressions: Dict[int, Set[str]], rule: str,
                  line: int) -> bool:
    rules = suppressions.get(line)
    return bool(rules) and ("all" in rules or rule in rules)


def audit(path: str, source: str) -> List[dict]:
    """Every inline disable in a file, for the baseline's suppression
    audit listing (so accepted escapes stay reviewable in one place)."""
    out = []
    for i, line in enumerate(source.splitlines(), start=1):
        m = _RE.search(line)
        if m:
            out.append({
                "path": path,
                "line": i,
                "kind": m.group(1),
                "rules": sorted(r.strip() for r in m.group(2).split(",")),
                "code": line.strip()[:160],
            })
    return out
