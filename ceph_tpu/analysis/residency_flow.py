"""tpusan's engine: interprocedural device-residency dataflow.

ROADMAP item 2 is a transfer problem, not a compute problem: the
storage path runs orders of magnitude below the measured transfer
ceiling because values silently ping-pong between host and device
(BENCH_r05: storage_path 0.054 GiB/s vs ceiling 239 GiB/s).  The
shallow jax rules could pattern-match ``np.asarray`` in a loop, but
they were blind to where an array actually LIVES -- they flagged host
arrays being converted (noise) and missed device arrays leaking through
a helper call (the real bug).

This module tracks a three-point lattice per value --

    ``device``  -- produced by ``jax.device_put``/``jnp.*``/a jitted
                   call/a callee that returns device values; stays
                   device through slicing, arithmetic and
                   shape-preserving methods;
    ``host``    -- produced by ``np.*``/``bytes``/``jax.device_get``/
                   literals;
    ``unknown`` -- parameters, ``self.*`` attributes, joins of
                   conflicting branches (rules only fire on *definite*
                   device values, so unknown is the safe default)

-- from producers through assignments, returns and direct + ``self.``
method calls (resolved by ``analysis/callgraph.py``'s tables).  Each
function gets a summary:

* ``returns``         -- lattice value of its return expression(s);
* ``syncs``           -- the body performs a definite D2H: an explicit
                         seam call (``jax.device_get``,
                         ``residency.device_get``) or an implicit sink
                         (``np.asarray``/``.tolist()``/``float()``/
                         iteration) applied to a device value --
                         directly or through a callee;
* ``syncing_params``  -- positions whose argument gets D2H-synced when
                         a device value is passed there (the
                         "transitively-syncing helper" information the
                         resident-section rule needs).

Summaries reach a module-wide fixpoint so ``self._land()`` three calls
deep still counts as a sync.  Like every cephlint component this is a
pure AST consumer -- nothing under analysis is imported or executed.

Module analyses are memoized on ``(path, source hash)`` -- the
mtime-cache role, but keyed by content so a touched-but-unchanged file
reuses its summary -- which keeps repeated scans (``--changed`` then
the full gate, bench's lint stage) from re-deriving the fixpoint.
"""

from __future__ import annotations

import ast
import hashlib
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ceph_tpu.analysis import callgraph as callgraph_mod
from ceph_tpu.analysis.core import FileContext, call_name, dotted_name

HOST = "host"
DEVICE = "device"
UNKNOWN = "unknown"

#: fixpoint bound (module-wide summary propagation; cycles converge)
_MAX_ROUNDS = 12

#: calls whose result is a device-resident array
DEVICE_PRODUCER_CALLS = {
    "jax.device_put", "jax.device_put_sharded", "jax.device_put_replicated",
    "residency.device_put", "residency.to_device", "_to_device",
    "accounted_device_matrix", "pipeline.accounted_device_matrix",
}
#: module prefixes whose calls produce device arrays
DEVICE_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.")

#: explicit D2H seams: ALWAYS a transfer, whatever the operand lattice
#: says (these are the sanctioned boundary edges -- legal outside a
#: resident section, a definite violation inside one)
EXPLICIT_D2H_CALLS = {
    "jax.device_get", "residency.device_get", "residency.to_host",
    "device_get", "to_host",  # the bare from-import spellings
}

#: implicit D2H sinks: a transfer iff the operand is device-resident
IMPLICIT_SINK_CALLS = {
    "np.asarray", "np.array", "np.ascontiguousarray", "np.frombuffer",
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "float", "int", "bytes", "list", "tuple",
}
#: sinks taking a SEQUENCE whose elements may be device arrays
IMPLICIT_SEQ_SINK_CALLS = {
    "np.stack", "np.concatenate", "numpy.stack", "numpy.concatenate",
}
#: method calls that pull the receiver to host
SINK_METHODS = {"tolist", "item"}
#: method calls that keep a device receiver on device
DEVICE_PRESERVING_METHODS = {
    "reshape", "astype", "transpose", "view", "copy", "ravel", "flatten",
    "sum", "min", "max", "squeeze", "swapaxes", "set", "add", "get",
    "block_until_ready",
}

#: host-producing calls (beyond the np prefix probe)
HOST_PRODUCER_CALLS = {
    "bytes", "bytearray", "len", "range", "sorted",
}
_NP_PREFIXES = ("np.", "numpy.")


def join(a: str, b: str) -> str:
    return a if a == b else UNKNOWN


class SyncSite:
    """One D2H transfer site inside a function body."""

    __slots__ = ("node", "kind", "desc", "operand")

    def __init__(self, node: ast.AST, kind: str, desc: str,
                 operand: Optional[ast.AST] = None):
        self.node = node
        #: "explicit" (device_get seam), "implicit" (sink on a device
        #: value), "helper" (call to a syncing callee), "param"
        #: (device argument passed at a callee's syncing position)
        self.kind = kind
        self.desc = desc
        self.operand = operand


class FunctionResidency:
    """Per-function residency facts + the interprocedural summary."""

    __slots__ = ("info", "names", "returns", "syncs", "sync_desc",
                 "syncing_params", "sync_sites", "param_names")

    def __init__(self, info):
        self.info = info  # callgraph.FunctionInfo
        self.names: Dict[str, str] = {}
        self.returns = UNKNOWN
        self.syncs = False
        self.sync_desc = ""
        self.syncing_params: Set[int] = set()
        self.sync_sites: List[SyncSite] = []
        args = info.node.args
        params = [a.arg for a in getattr(args, "posonlyargs", [])] + \
                 [a.arg for a in args.args]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        self.param_names: List[str] = params


class ModuleResidency:
    """Residency lattice + summaries for every function in one module."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.graph = callgraph_mod.get(ctx)
        #: qualname -> FunctionResidency
        self.functions: Dict[str, FunctionResidency] = {
            q: FunctionResidency(info)
            for q, info in self.graph.functions.items()
        }
        self._fixpoint()

    # -- interprocedural fixpoint ------------------------------------------

    def _fixpoint(self) -> None:
        for _ in range(_MAX_ROUNDS):
            changed = False
            for fr in self.functions.values():
                before = (fr.returns, fr.syncs,
                          frozenset(fr.syncing_params))
                self._analyze(fr)
                if (fr.returns, fr.syncs,
                        frozenset(fr.syncing_params)) != before:
                    changed = True
            if not changed:
                break

    # -- queries -----------------------------------------------------------

    def of_node(self, node: ast.AST) -> Optional[FunctionResidency]:
        info = self.graph.by_node.get(node)
        if info is None:
            return None
        return self.functions.get(info.qualname)

    def resolve_call(self, fr: FunctionResidency,
                     call: ast.Call) -> Optional[FunctionResidency]:
        qual = self.graph._resolve_call(fr.info, call)
        if qual is None:
            return None
        return self.functions.get(qual)

    # -- per-function analysis ---------------------------------------------

    def _analyze(self, fr: FunctionResidency) -> None:
        """(Re)compute one function's lattice, sink sites and summary
        given the current callee summaries.  Flow-insensitive over the
        body (two passes settle forward+backward name references)."""
        fr.sync_sites = []
        fr.syncs = False
        fr.sync_desc = ""
        fr.syncing_params = set()
        for _ in range(2):
            for node in self._own_stmts_and_exprs(fr.info.node):
                if isinstance(node, ast.Assign):
                    res = self.expr_res(fr, node.value)
                    for tgt in node.targets:
                        self._bind(fr, tgt, res)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    self._bind(fr, node.target,
                               self.expr_res(fr, node.value))
                elif isinstance(node, ast.AugAssign):
                    if isinstance(node.target, ast.Name):
                        res = join(
                            fr.names.get(node.target.id, UNKNOWN),
                            self.expr_res(fr, node.value))
                        fr.names[node.target.id] = res
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    # iterating a device array: each element stays a
                    # device scalar/row (and the loop is a sink, see
                    # below)
                    res = self.expr_res(fr, node.iter)
                    self._bind(fr, node.target,
                               DEVICE if res == DEVICE else UNKNOWN)
        # final pass: collect sink sites + returns with settled names
        returns: List[str] = []
        for node in self._own_stmts_and_exprs(fr.info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                returns.append(self.expr_res(fr, node.value))
            self._collect_sinks(fr, node)
        fr.returns = returns[0] if returns else UNKNOWN
        for r in returns[1:]:
            fr.returns = join(fr.returns, r)
        # summary: any definite sink makes the function syncing
        for site in fr.sync_sites:
            if not fr.syncs:
                fr.syncs = True
                fr.sync_desc = site.desc
            # a sink whose operand is a bare (never locally re-bound to
            # host) parameter marks that position syncing
            op = site.operand
            if isinstance(op, ast.Name) and op.id in fr.param_names and \
                    fr.names.get(op.id, UNKNOWN) != HOST:
                fr.syncing_params.add(fr.param_names.index(op.id))

    def _bind(self, fr: FunctionResidency, target: ast.expr,
              res: str) -> None:
        if isinstance(target, ast.Name):
            fr.names[target.id] = res
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(fr, elt, UNKNOWN)

    @staticmethod
    def _own_stmts_and_exprs(fn: ast.AST) -> Iterator[ast.AST]:
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- expression lattice -------------------------------------------------

    def expr_res(self, fr: FunctionResidency, e: ast.AST,
                 depth: int = 0) -> str:
        if depth > 24:
            return UNKNOWN
        if isinstance(e, ast.Name):
            return fr.names.get(e.id, UNKNOWN)
        if isinstance(e, ast.Constant):
            return HOST
        if isinstance(e, ast.Call):
            return self._call_res(fr, e, depth)
        if isinstance(e, ast.Subscript):
            # slicing/indexing a device array yields a device array
            base = self.expr_res(fr, e.value, depth + 1)
            return DEVICE if base == DEVICE else UNKNOWN
        if isinstance(e, (ast.BinOp,)):
            left = self.expr_res(fr, e.left, depth + 1)
            right = self.expr_res(fr, e.right, depth + 1)
            if DEVICE in (left, right):
                return DEVICE  # device op promotes the result to device
            if left == right == HOST:
                return HOST
            return UNKNOWN
        if isinstance(e, ast.UnaryOp):
            return self.expr_res(fr, e.operand, depth + 1)
        if isinstance(e, ast.IfExp):
            return join(self.expr_res(fr, e.body, depth + 1),
                        self.expr_res(fr, e.orelse, depth + 1))
        if isinstance(e, ast.Attribute):
            # x.T / x.at on a device value stays device; anything else
            # (self.foo, module attrs) is unknown
            if e.attr in ("T", "at", "mT") and \
                    self.expr_res(fr, e.value, depth + 1) == DEVICE:
                return DEVICE
            return UNKNOWN
        if isinstance(e, ast.Await):
            return self.expr_res(fr, e.value, depth + 1)
        return UNKNOWN

    def _call_res(self, fr: FunctionResidency, call: ast.Call,
                  depth: int) -> str:
        name = call_name(call)
        if name in DEVICE_PRODUCER_CALLS or \
                name.startswith(DEVICE_PRODUCER_PREFIXES):
            return DEVICE
        if name in EXPLICIT_D2H_CALLS or name in HOST_PRODUCER_CALLS or \
                name.startswith(_NP_PREFIXES) or \
                name in IMPLICIT_SINK_CALLS or \
                name in IMPLICIT_SEQ_SINK_CALLS:
            return HOST
        # method call: residency-preserving ops keep the receiver's home
        if isinstance(call.func, ast.Attribute):
            recv = self.expr_res(fr, call.func.value, depth + 1)
            if call.func.attr in SINK_METHODS:
                return HOST
            if call.func.attr in DEVICE_PRESERVING_METHODS and \
                    recv == DEVICE:
                return DEVICE
        callee = self.resolve_call(fr, call)
        if callee is not None:
            from ceph_tpu.analysis.core import is_jitted

            if is_jitted(callee.info.node):
                return DEVICE  # a jitted call returns device arrays
            return callee.returns
        return UNKNOWN

    # -- sink collection ----------------------------------------------------

    def _collect_sinks(self, fr: FunctionResidency, node: ast.AST) -> None:
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if self.expr_res(fr, node.iter) == DEVICE:
                fr.sync_sites.append(SyncSite(
                    node, "implicit",
                    "Python iteration over a device array (one blocking "
                    "D2H per element)",
                    node.iter))
            return
        if not isinstance(node, ast.Call):
            return
        name = call_name(node)
        operand = node.args[0] if node.args else None
        if name in EXPLICIT_D2H_CALLS:
            fr.sync_sites.append(SyncSite(
                node, "explicit", f"{name}(...) is an explicit D2H edge",
                operand))
            return
        if name in IMPLICIT_SINK_CALLS and operand is not None:
            res = self.expr_res(fr, operand)
            if res == DEVICE:
                fr.sync_sites.append(SyncSite(
                    node, "implicit",
                    f"{name}(...) on a device-resident value pulls it "
                    "to host", operand))
            elif isinstance(operand, ast.Name) and \
                    operand.id in fr.param_names and res != HOST:
                # sink on a parameter of unknown residency: the
                # function syncs WHATEVER it is handed -- callers
                # passing a device value get flagged at the call site
                fr.syncing_params.add(fr.param_names.index(operand.id))
            return
        if name in IMPLICIT_SEQ_SINK_CALLS and operand is not None:
            elts = operand.elts if isinstance(
                operand, (ast.List, ast.Tuple)) else [operand]
            for elt in elts:
                # a comprehension over a device array D2Hs every element
                if isinstance(elt, (ast.ListComp, ast.GeneratorExp)) and \
                        any(self.expr_res(fr, gen.iter) == DEVICE
                            for gen in elt.generators):
                    fr.sync_sites.append(SyncSite(
                        node, "implicit",
                        f"{name}(...) gathers elements of a device "
                        "array to host", elt))
                    return
                if self.expr_res(fr, elt) == DEVICE:
                    fr.sync_sites.append(SyncSite(
                        node, "implicit",
                        f"{name}(...) on device-resident value(s) pulls "
                        "them to host", elt))
                    return
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in SINK_METHODS:
            recv = node.func.value
            res = self.expr_res(fr, recv)
            if res == DEVICE:
                fr.sync_sites.append(SyncSite(
                    node, "implicit",
                    f".{node.func.attr}() on a device-resident value "
                    "pulls it to host", recv))
            elif isinstance(recv, ast.Name) and \
                    recv.id in fr.param_names and res != HOST:
                fr.syncing_params.add(fr.param_names.index(recv.id))
            return
        # interprocedural: a call to a syncing module-local helper, or a
        # device argument handed to a callee position that syncs it
        callee = self.resolve_call(fr, node)
        if callee is None or callee is fr:
            return
        if callee.syncs:
            fr.sync_sites.append(SyncSite(
                node, "helper",
                f"{name}() syncs to host inside its body "
                f"({callee.sync_desc})", None))
            return
        if callee.syncing_params:
            for idx, arg in enumerate(node.args):
                if idx in callee.syncing_params and \
                        self.expr_res(fr, arg) == DEVICE:
                    fr.sync_sites.append(SyncSite(
                        node, "param",
                        f"{name}() D2H-syncs its argument "
                        f"{callee.param_names[idx]!r} and this call "
                        "passes a device-resident value there", arg))
                    return


# -- memoization ------------------------------------------------------------

#: path -> (source blake2 digest, ModuleResidency); content-keyed so a
#: rescan of an unchanged file (``--changed`` then the full gate, bench)
#: reuses the fixpoint instead of re-deriving it
_CACHE: Dict[str, Tuple[bytes, ModuleResidency]] = {}
_CACHE_MAX = 512


def get(ctx: FileContext) -> ModuleResidency:
    digest = hashlib.blake2b(ctx.source.encode("utf-8", "replace"),
                             digest_size=16).digest()
    hit = _CACHE.get(ctx.path)
    if hit is not None and hit[0] == digest:
        return hit[1]
    analysis = ModuleResidency(ctx)
    if len(_CACHE) >= _CACHE_MAX:
        _CACHE.clear()
    _CACHE[ctx.path] = (digest, analysis)
    return analysis
