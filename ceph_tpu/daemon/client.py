"""Client for a multi-process cluster: ECBackend over TCP.

The primary-side EC engine (placement, write pipeline, reconstruct) runs
in the client process -- exactly the reference's model where librados'
Objecter computes placement client-side and the *primary OSD* runs
ECBackend; our minimized design already fuses those roles in ECBackend
(see osd/ecbackend.py), so pointing it at a TCPMessenger yields the
remote cluster client.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

from ceph_tpu.msg.tcp import TCPMessenger
from ceph_tpu.osd.ecbackend import ECBackend
from ceph_tpu.plugins import registry as registry_mod


class RemoteClient:
    def __init__(self, backend: ECBackend, messenger: TCPMessenger,
                 n_osds: int):
        self.backend = backend
        self.messenger = messenger
        self.n_osds = n_osds

    @classmethod
    async def connect(
        cls,
        addr_map: "str | Dict[str, Tuple[str, int]]",
        profile: Dict[str, str],
        name: str = "client",
        hosts=None,
        keyring=None,
    ) -> "RemoteClient":
        if isinstance(addr_map, str):
            with open(addr_map) as f:
                addr_map = {k: tuple(v) for k, v in json.load(f).items()}
        if isinstance(keyring, str):
            from ceph_tpu.auth import KeyRing

            keyring = KeyRing.load(keyring)
        n_osds = sum(1 for k in addr_map if k.startswith("osd."))
        messenger = TCPMessenger(name, addr_map, keyring=keyring)
        await messenger.start()

        profile = dict(profile)
        plugin = profile.pop("plugin", "jerasure")
        ec = registry_mod.instance().factory(plugin, profile)
        from ceph_tpu.osd.placement import CrushPlacement

        placement = CrushPlacement(n_osds, ec.get_chunk_count(), hosts=hosts)
        backend = ECBackend(
            ec, list(range(n_osds)), messenger, name=name,
            placement=placement,
        )
        return cls(backend, messenger, n_osds)

    async def probe_osds(self) -> Dict[str, bool]:
        """Heartbeat round: refresh the liveness view of every OSD."""
        out = {}
        for i in range(self.n_osds):
            name = f"osd.{i}"
            out[name] = await self.messenger.probe(name)
        return out

    # -- I/O surface -------------------------------------------------------

    async def write(self, oid: str, data: bytes) -> None:
        await self.backend.write(oid, data)

    async def read(self, oid: str) -> bytes:
        return await self.backend.read(oid)

    async def write_range(self, oid: str, offset: int, data: bytes) -> None:
        await self.backend.write_range(oid, offset, data)

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        return await self.backend.read_range(oid, offset, length)

    async def close(self) -> None:
        await self.messenger.shutdown()
