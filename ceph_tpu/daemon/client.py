"""Client for a multi-process cluster: a thin Objecter over TCP.

Round-3 architecture (the reference's): the client computes placement
(the librados Objecter role, src/osdc/Objecter.cc:2784 _calc_target) and
sends ONE op per I/O to the primary OSD daemon, which hosts the EC
engine and fans out sub-ops to the acting set
(src/osd/PrimaryLogPG.cc do_op; src/osd/ECBackend.cc:1976 fan-out).
If the primary dies mid-op the Objecter probes it, marks it down and
retries against the next up shard -- primary failover without any
client-side chunk work.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

from ceph_tpu.msg.tcp import TCPMessenger
from ceph_tpu.osd.objecter import Objecter
from ceph_tpu.osd.placement import CrushPlacement
from ceph_tpu.plugins import registry as registry_mod


class RemoteClient:
    def __init__(self, backend: Objecter, messenger: TCPMessenger,
                 n_osds: int):
        self.backend = backend
        self.messenger = messenger
        self.n_osds = n_osds

    @classmethod
    async def connect(
        cls,
        addr_map: "str | Dict[str, Tuple[str, int]]",
        profile: Dict[str, str],
        name: str = "client",
        hosts=None,
        keyring=None,
        pool: str = "ecpool",
        op_timeout: float = 30.0,
    ) -> "RemoteClient":
        if isinstance(addr_map, str):
            from ceph_tpu.utils import aio

            addr_map = {
                k: tuple(v)
                for k, v in (await aio.read_json(addr_map)).items()
            }
        if isinstance(keyring, str):
            from ceph_tpu.auth import KeyRing

            keyring = KeyRing.load(keyring)
        n_osds = sum(1 for k in addr_map if k.startswith("osd."))
        messenger = TCPMessenger(name, addr_map, keyring=keyring)
        await messenger.start()

        # the client needs only the pool width (k+m, or replica count)
        # for placement; chunk math happens on the primary OSD
        profile = dict(profile)
        if profile.pop("pool_type", "erasure") == "replicated":
            km = int(profile.get("size", 3))
        else:
            plugin = profile.pop("plugin", "jerasure")
            ec = registry_mod.instance().factory(plugin, profile)
            km = ec.get_chunk_count()
        placement = CrushPlacement(n_osds, km, hosts=hosts)
        backend = Objecter(
            messenger, km, n_osds, placement=placement, name=name,
            pool=pool, op_timeout=op_timeout,
        )
        client = cls(backend, messenger, n_osds)
        n_mons = sum(1 for k in addr_map if k.startswith("mon."))
        if n_mons:
            # map-driven routing (reference Objecter::_maybe_request_map):
            # subscribe to osdmap epochs; up/down marks and CRUSH weights
            # come from the mon, not just from client-side probing
            from ceph_tpu.mon.monitor import MonClient
            from ceph_tpu.mon.osdmap import apply_map_view

            monc = MonClient(messenger, n_mons, name)
            state = {"epoch": 0}

            async def mon_hook(msg):
                if await monc.handle_reply(msg):
                    return
                if msg.get("type") == "osdmap":
                    apply_map_view(msg["map"], state, messenger,
                                   placements=[placement])

            backend.mon_hook = mon_hook
            client.monc = monc
            await monc.subscribe()
        return client

    async def probe_osds(self) -> Dict[str, bool]:
        """Heartbeat round: refresh the liveness view of every OSD."""
        out = {}
        for i in range(self.n_osds):
            name = f"osd.{i}"
            out[name] = await self.messenger.probe(name)
        return out

    # -- I/O surface -------------------------------------------------------

    async def write(self, oid: str, data: bytes) -> None:
        await self.backend.write(oid, data)

    async def read(self, oid: str) -> bytes:
        return await self.backend.read(oid)

    async def write_range(self, oid: str, offset: int, data: bytes) -> None:
        await self.backend.write_range(oid, offset, data)

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        return await self.backend.read_range(oid, offset, length)

    async def close(self) -> None:
        await self.messenger.shutdown()
