"""ceph-mgr daemon: the wire-fed telemetry endpoint over TCP.

Reference boot flow: src/ceph_mgr.cc -- global init, messengers,
MgrStandby::init; DaemonServer accepts every daemon's MgrClient session
and folds MMgrReport/MPGStats into the cluster map.  Here:

  python -m ceph_tpu.daemon.mgr --rank 0 --addr-map map.json \
      [--http-port P] [--admin-socket PATH]

``map.json`` must name this mgr (``mgr.R``).  OSD/mon daemons discover
``mgr.*`` entries in the same map and run their ReportSender loops
against them (ceph_tpu/mgr/report.py).  The process prints
``mgr.R up [http PORT]`` once both the messenger socket and the HTTP
endpoint listen; health/status/pg-stat are served over the admin socket
(tools/rados_cli.py status / health / pg stat) and /metrics /health
/status over HTTP (the prometheus scrape).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys


async def serve(args) -> None:
    from ceph_tpu.mgr.pgmap import MgrServer
    from ceph_tpu.mgr.report import LoopLagProbe
    from ceph_tpu.msg.tcp import TCPMessenger
    from ceph_tpu.utils import aio

    addr_map = {
        k: tuple(v)
        for k, v in (await aio.read_json(args.addr_map)).items()
    }
    name = f"mgr.{args.rank}"
    keyring = None
    if args.keyring:
        from ceph_tpu.auth import KeyRing

        keyring = KeyRing.load(args.keyring)
    messenger = TCPMessenger(name, addr_map, keyring=keyring)
    await messenger.start()
    mgr = MgrServer(name, messenger, addr_map=addr_map,
                    http_port=args.http_port)
    http_port = await mgr.start_http()
    # the mgr watches its own event loop too (it is a daemon like any
    # other; a lagging mgr mis-dates every staleness judgement)
    lag = LoopLagProbe()
    lag.start(messenger, name)

    asok = None
    if args.admin_socket:
        from ceph_tpu.utils.admin_socket import AdminSocket

        asok = AdminSocket(args.admin_socket)
        asok.register("status", lambda cmd: mgr.pgmap.dump())
        asok.register("status text",
                      lambda cmd: {"text": mgr.pgmap.status_text()})
        asok.register("health", lambda cmd: mgr.pgmap.health())
        asok.register("pg stat", lambda cmd: mgr.pgmap.pg_stat())
        asok.register("metrics",
                      lambda cmd: {"text": mgr.pgmap.prometheus_text()})
        # the mgr-local cluster event log (clog analogue): health
        # transitions + slow-op warnings, rendered by `rados_cli log`
        asok.register("log last", lambda cmd: {
            "lines": mgr.pgmap.clog.last(int(cmd.get("count", 20))),
        })
        asok.register("mgr status", lambda cmd: {
            "name": name,
            "http_port": http_port,
            "daemons_reporting": len(mgr.pgmap.daemons),
            "reports_folded": mgr.pgmap.reports_folded,
            "beacons_folded": mgr.pgmap.beacons_folded,
            "lag_ms": round(lag.lag_ms, 3),
        })
        await asok.start()
    print(f"{name} up http {http_port}", flush=True)

    # startup warm-up is over: freeze the boot heap out of the
    # collector (gc_freeze_on_start; the r19 gc-pause-tax fix)
    from ceph_tpu.utils import gcopt

    gcopt.freeze_after_warmup()
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if asok is not None:
        await asok.stop()
    await mgr.stop()
    await messenger.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--addr-map", required=True)
    ap.add_argument("--http-port", type=int, default=0,
                    help="prometheus/health HTTP port (0 = ephemeral; "
                         "printed on the readiness line)")
    ap.add_argument("--keyring", default="",
                    help="keyring file enabling cephx-style auth")
    ap.add_argument("--admin-socket", default="",
                    help="unix socket for status/health/pg-stat "
                         "introspection (rados_cli reads it)")
    args = ap.parse_args(argv)
    try:
        asyncio.run(serve(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
