"""ceph-osd daemon: one OSD process serving EC sub-ops over TCP.

Reference boot flow: src/ceph_osd.cc (SURVEY.md §3.4) -- global init,
ObjectStore::create, messengers, OSD::init.  Here:

  python -m ceph_tpu.daemon.osd --id N --addr-map map.json \
      [--objectstore filestore --data-path DIR] [--op-queue wpq]

``map.json`` is the cluster address book: {"osd.0": ["127.0.0.1", 7000],
..., "client": [...]} (the vstart harness writes it).  The process prints
``osd.N up`` once the socket is listening (the harness's readiness
signal) and runs until SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys


async def _mon_integrate(args, shard, messenger, addr_map,
                         n_mons: int) -> None:
    """Boot this OSD into the monitor cluster.

    Reference flow (src/osd/OSD.cc:5386-5513 start_boot/_send_boot +
    :4612 handle_osd_ping):

    * ``osd boot`` registers the daemon; the mon marks it up and bumps
      the osdmap epoch;
    * a subscription streams every committed osdmap; the daemon applies
      up/down marks to its messenger, pushes CRUSH weights into hosted
      placements, and HOSTS POOLS it learns from the map (pool create
      flows mon -> daemons, not from a static file);
    * a heartbeat loop probes every peer OSD; a peer silent past
      ``osd_heartbeat_grace`` is reported via ``osd failure``, and the
      mon marks it down once ``mon_osd_min_down_reporters`` distinct
      daemons agree.
    """
    import asyncio

    from ceph_tpu.mon.monitor import MonClient
    from ceph_tpu.utils.config import get_config

    from ceph_tpu.mon.osdmap import apply_map_view

    name = shard.name
    monc = MonClient(messenger, n_mons, name)
    n_osds = sum(1 for k in addr_map if k.startswith("osd."))
    state = {"epoch": 0, "up": {}}
    flags = {"booting": False}
    loop = asyncio.get_event_loop()

    def apply_osdmap(m: dict) -> None:
        if not apply_map_view(
            m, state, messenger,
            placements=[b.placement for b in shard.pools.values()],
            skip_entity=name,
        ):
            return
        if not state["up"].get(shard.osd_id, True) and \
                not flags["booting"]:
            # the map says WE are down but this process is alive (a
            # spurious mark-down): re-boot into the mon (reference
            # OSD::_committed_osd_maps -> start_boot)
            flags["booting"] = True
            messenger.adopt_task(f"{name}.reboot", loop.create_task(boot()))
        # pools flow mon -> daemon: host engines for map pools we lack
        from ceph_tpu.osd.placement import CrushPlacement

        for pname, p in m.get("pools", {}).items():
            if pname in shard.pools:
                # the cache-tier mode flows with every map epoch
                # (`osd tier cache-mode` commits -> broadcast -> here)
                shard.pools[pname].tier_mode = p.get("cache_mode", "none")
                continue
            if p.get("pool_type") == "replicated":
                ec, km = None, int(p["size"])
            else:
                profile = dict(
                    m.get("ec_profiles", {}).get(p["profile_name"], {})
                )
                if not profile:
                    continue  # profile missing from the map: skip
                plugin = profile.pop("plugin", "jerasure")
                from ceph_tpu.plugins import registry as registry_mod

                ec = registry_mod.instance().factory(plugin, profile)
                km = ec.get_chunk_count()
            placement = CrushPlacement(n_osds, km, hosts=p.get("hosts"))
            # seed the fresh placement through the shared gate (fresh
            # view state, so the current epoch applies): weight pushes
            # AND elastic map growth stay in one place -- a raw
            # weights[] loop here IndexError'd on ids past n_osds
            apply_map_view(m, {}, None, placements=[placement])
            hosted = shard.host_pool(
                pname, ec, n_osds, placement,
                pool_type=p.get("pool_type", "erasure"),
                size=km, min_size=p.get("min_size") or None,
            )
            hosted.tier_mode = p.get("cache_mode", "none")
        # elastic growth: widen every hosted engine's membership view
        # to the map's max_osd, so peering enumerates osds that joined
        # after boot (ids the addr map doesn't name yet read as down
        # on the messenger until their daemon actually connects)
        for b in shard.pools.values():
            for j in range(len(b.osds), int(m.get("max_osd", 0))):
                b.osds.append(j)
        shard.request_peering()  # re-peer on every map epoch

    async def mon_hook(src, msg):
        if await monc.handle_reply(msg):
            return
        if msg.get("type") == "osdmap":
            apply_osdmap(msg["map"])

    shard.mon_hook = mon_hook

    async def boot():
        flags["booting"] = True
        try:
            while True:
                rc, _out = await monc.command(
                    {"prefix": "osd boot", "osd": shard.osd_id}, timeout=2.0
                )
                if rc == 0:
                    break
                await asyncio.sleep(0.5)  # mons still electing
            await monc.subscribe()
        finally:
            flags["booting"] = False

    async def heartbeat_loop():
        # peer heartbeats + failure reports (OSD.cc:4612 handle_osd_ping
        # -> send_failures).  Steady state is a cheap ping/pong over the
        # CACHED connection (the review found per-round probe() cycling
        # every peer's TCP connection); the expensive probe runs only to
        # CONFIRM a peer whose pongs went silent past the grace.
        # membership follows the map: added osds join the ping rounds,
        # removed ones drop out (a boot-frozen list would report a
        # removed id as failed forever)
        def current_peers():
            ids = sorted(state["up"]) if state["up"] else range(n_osds)
            return [j for j in ids if f"osd.{j}" != name]

        start = loop.time()
        for j in current_peers():  # never-ponged peers age from start
            shard.hb_pongs.setdefault(f"osd.{j}", start)
        # budget-bounded fan-out (async-unbounded-fanout): the gathered
        # ping round holds at most this many coroutines in flight no
        # matter how many peers the map grows to
        hb_budget = asyncio.Semaphore(32)

        async def ping_one(j):
            async with hb_budget:
                try:
                    # bound the send: a blackholed peer's TCP connect
                    # would otherwise stall the whole gathered round for
                    # the OS SYN timeout (review r5 finding)
                    await asyncio.wait_for(
                        messenger.send_message(name, f"osd.{j}", "ping"),
                        timeout=1.0)
                except (OSError, asyncio.TimeoutError):
                    pass  # dead peer: pong stays stale, the grace fires

        async def confirm_down(j):
            async with hb_budget:
                try:
                    return not await messenger.probe(
                        f"osd.{j}", timeout=1.0)
                except (OSError, asyncio.TimeoutError):
                    return True

        while True:
            cfg = get_config()
            await asyncio.sleep(float(cfg.get_val("osd_heartbeat_interval")))
            grace = float(cfg.get_val("osd_heartbeat_grace"))
            peers = current_peers()
            now0 = loop.time()
            for j in peers:  # a just-added peer ages from this round
                shard.hb_pongs.setdefault(f"osd.{j}", now0)
            await asyncio.gather(*(ping_one(j) for j in peers))
            now = loop.time()
            suspects = [
                j for j in peers
                if now - shard.hb_pongs.get(f"osd.{j}", start) >= grace
                and state["up"].get(j, True)
            ]
            if not suspects:
                continue
            confirmed = await asyncio.gather(*(
                confirm_down(j) for j in suspects
            ))
            for j, down in zip(suspects, confirmed):
                if not down:
                    shard.hb_pongs[f"osd.{j}"] = now  # probe answered
                    continue
                # report once per grace window; the mon dedups reporters
                # and the map broadcast stops the loop
                shard.hb_pongs[f"osd.{j}"] = now
                await monc.command(
                    {"prefix": "osd failure", "osd": j, "from": name},
                    timeout=1.0,
                )

    messenger.adopt_task(f"{name}.boot", loop.create_task(boot()))
    messenger.adopt_task(
        f"{name}.heartbeat", loop.create_task(heartbeat_loop())
    )
    shard.start_tick()


async def serve(args) -> None:
    from ceph_tpu.msg.tcp import TCPMessenger
    from ceph_tpu.osd.ecbackend import OSDShard

    from ceph_tpu.utils import aio

    addr_map = {
        k: tuple(v)
        for k, v in (await aio.read_json(args.addr_map)).items()
    }
    name = f"osd.{args.id}"
    keyring = None
    if args.keyring:
        from ceph_tpu.auth import KeyRing

        keyring = KeyRing.load(args.keyring)
    messenger = TCPMessenger(name, addr_map, keyring=keyring)
    mon_ranks = sorted(
        int(k.split(".", 1)[1]) for k in addr_map if k.startswith("mon.")
    )
    conf = None
    if args.cluster_conf and not mon_ranks:
        # read the pool conf BEFORE the socket listens: the moment
        # start() returns, peers replay queued lossless sub-ops (a
        # revived OSD's backlog), and the stretch from listen to
        # host_pool below must stay yield-free or early ops are
        # dispatched into a shard that "hosts no pool"
        conf = await aio.read_json(args.cluster_conf)
    await messenger.start()
    # The PR-2 invariant, now machine-enforced: the socket is LISTENING
    # from the moment start() returns, and peers immediately replay
    # queued lossless sub-ops (a revived OSD's backlog).  The stretch
    # from here to host_pool below must stay yield-free, or early ops
    # are dispatched into a shard that "hosts no pool" (the cluster
    # conf is read BEFORE start() for exactly this reason).  The static
    # rule flags any await inside; the runtime verifier
    # (analysis/runtime.py) asserts no task switch lands here in tier-1.
    # cephlint: atomic-section osd-listen-to-host-pool
    shard = OSDShard(
        args.id, messenger, op_queue=args.op_queue,
        objectstore=args.objectstore, data_path=args.data_path,
    )
    if conf is not None:
        # legacy static bring-up: host a primary engine for the cluster's
        # pool from a JSON conf: THIS daemon (not the client) owns
        # placement, version authority and sub-op fan-out for objects
        # whose acting set it leads (the PrimaryLogPG role)
        profile = dict(conf["profile"])
        from ceph_tpu.osd.placement import CrushPlacement

        n_osds = sum(1 for k in addr_map if k.startswith("osd."))
        pool_type = profile.pop("pool_type", conf.get("pool_type", "erasure"))
        if pool_type == "replicated":
            # TYPE_REPLICATED pool (reference build_pg_backend,
            # src/osd/PGBackend.cc:533-570): size full copies, no codec
            ec = None
            km = int(profile.get("size", 3))
        else:
            plugin = profile.pop("plugin", "jerasure")
            from ceph_tpu.plugins import registry as registry_mod

            ec = registry_mod.instance().factory(plugin, profile)
            km = ec.get_chunk_count()
        placement = CrushPlacement(n_osds, km, hosts=conf.get("hosts"))
        shard.host_pool(conf.get("pool", "ecpool"), ec, n_osds, placement,
                        pool_type=pool_type, size=km)
        # daemons run peering-driven auto recovery by default (OSD::tick)
        shard.start_tick()
    # cephlint: end-atomic-section
    if mon_ranks:
        # monitor-integrated boot (reference src/ceph_osd.cc:650 ->
        # OSD::start_boot, src/osd/OSD.cc:5386): register with the mon,
        # subscribe to osdmap epochs, learn pools FROM the map, run peer
        # heartbeats and report failures -- no static pool conf needed.
        # (Mon-learned pools arrive via osdmap broadcasts; replayed
        # sub-ops for them are refused un-acked until the map applies,
        # so this branch may yield -- it sits OUTSIDE the section.)
        await _mon_integrate(args, shard, messenger, addr_map,
                             len(mon_ranks))
    # MgrClient report loop (ceph_tpu/mgr/report.py): when the address
    # map names mgr daemons, beacon + report frames flow to every one
    # of them -- cluster health/status/pg-stat over real TCP derive
    # from THESE frames, never from in-process introspection.  No mgr
    # in the map = telemetry off, zero overhead (the bench baseline).
    from ceph_tpu.mgr.report import ReportSender, mgr_targets_from

    reporter = None
    mgr_targets = mgr_targets_from(addr_map)
    if mgr_targets:
        reporter = ReportSender(name, messenger, shard.mgr_report_stats,
                                mgr_targets, perf=shard.perf)
        reporter.start()
    # admin socket (src/common/admin_socket.cc): perf dump / ops /
    # config show / status over a unix socket next to the data dir
    asok = None
    if args.admin_socket or args.data_path:
        from ceph_tpu.utils.admin_socket import AdminSocket
        from ceph_tpu.utils.config import get_config

        asok_path = args.admin_socket or f"{args.data_path}/{name}.asok"
        asok = AdminSocket(asok_path)
        asok.register("perf dump", lambda cmd: shard.perf.snapshot())
        asok.register("perf histogram dump",
                      lambda cmd: shard.op_hist.snapshot())
        asok.register(
            "ops", lambda cmd: shard.optracker.dump_ops_in_flight()
        )
        asok.register(
            "dump_ops_in_flight",
            lambda cmd: shard.optracker.dump_ops_in_flight(),
        )
        asok.register(
            "dump_historic_ops",
            lambda cmd: shard.optracker.dump_historic_ops(),
        )
        asok.register(
            "dump_historic_slow_ops",
            lambda cmd: shard.optracker.dump_historic_slow_ops(),
        )

        def _trace_status(cmd):
            from ceph_tpu.utils import trace

            return dict(trace.status(), name=name)

        def _trace_dump(cmd):
            from ceph_tpu.utils import trace

            tid = cmd.get("trace_id")
            if tid is not None:
                return trace.dump_trace(int(tid))
            if cmd.get("slow"):
                return trace.dump_slow(cmd.get("count"))
            return trace.dump()

        asok.register("trace status", _trace_status)
        asok.register("trace dump", _trace_dump)

        # wire-tax profiler hooks (ceph_tpu/profiling/): status/dump/
        # reset; enable at runtime via `config set profile_mode on`
        # (the config-set hook below re-applies through configure())
        from ceph_tpu import profiling

        asok.register("profile status",
                      lambda cmd: dict(profiling.asok_status(cmd),
                                       name=name))
        asok.register("profile dump", profiling.asok_dump)
        asok.register("profile reset", profiling.asok_reset)
        # a runtime `config set profile_mode on` installs/uninstalls
        # the profiler arms through the normal observer plumbing
        get_config().add_observer(
            lambda changed: profiling.configure()
            if "profile_mode" in changed else None)
        profiling.configure()  # apply env/conf-selected mode at boot
        asok.register(
            "config show", lambda cmd: get_config().show_config()
        )
        asok.register(
            "config set",
            lambda cmd: get_config().apply_changes(
                {cmd["key"]: cmd["value"]}
            ) or {"success": True},
        )
        def _live_objects():
            # removal tombstones are durable state but not live objects:
            # ls and df must agree the deleted name is gone.  Two kinds:
            # meta-plane tombstones (_meta_removed) and replicated-pool
            # data tombstones (whiteout "removed",
            # ceph_tpu/osd/replicated.py).
            from ceph_tpu.osd.pg import WHITEOUT_KEY

            out = []
            for o in shard.store.list_objects():
                if o.endswith("@meta") and \
                        shard.store.getattr(o, "_meta_removed"):
                    continue
                if shard.store.getattr(o, WHITEOUT_KEY) == "removed":
                    continue
                out.append(o)
            return out

        asok.register("status", lambda cmd: {
            "name": name,
            "objects": len(_live_objects()),
            "pools": sorted(shard.pools),
        })
        asok.register("list_objects", lambda cmd: sorted(_live_objects()))
        asok.register("tier status", lambda cmd: dict(
            shard.tier.status(), name=name,
            modes={p: b.tier_mode for p, b in shard.pools.items()},
        ))
        def _residency_status(cmd):
            from ceph_tpu.analysis import residency

            return residency.status()

        asok.register("residency status", _residency_status)

        def _recovery_status(cmd):
            # background data-plane health (osd/recovery.py): batched
            # rebuild counters, scrub cursor progress, throttle
            # preemptions, per-pool dirty (pg_missing) depth and knobs
            snap = shard.perf.snapshot()
            return {
                "name": name,
                "batched": bool(get_config().get_val(
                    "osd_recovery_batched")),
                "counters": {
                    key: snap.get(key, 0)
                    for key in ("recovery_ops_batched", "recovery_bytes",
                                "recovery_batches", "recovery_preempted",
                                "recover", "recover_window",
                                "recover_failed", "scrub_chunks",
                                "tier_promote_from_recovery")
                },
                "client_ops_queued": shard._client_ops_queued,
                "dirty_objects": {
                    pool: len(b._dirty) + len(b._dirty_meta)
                    for pool, b in shard.pools.items()
                },
                "knobs": {
                    key: get_config().get_val(key)
                    for key in ("osd_recovery_max_active",
                                "osd_recovery_batch_bytes",
                                "osd_recovery_sleep",
                                "osd_scrub_chunk_max",
                                "osd_tier_promote_on_recovery")
                },
            }

        asok.register("recovery status", _recovery_status)
        asok.register("hit_set ls", lambda cmd: shard.hitsets.dump())
        asok.register("hit_set temperature", lambda cmd: {
            "oid": cmd.get("oid", ""),
            "temperature": shard.hitsets.temperature(cmd.get("oid", "")),
        })
        from ceph_tpu.utils import perfglue

        perfglue.register(asok)  # cpu_profiler start/stop/status
        await asok.start()
    print(f"{name} up", flush=True)

    # startup warm-up is over: freeze the boot heap out of the
    # collector (gc_freeze_on_start; the r19 gc-pause-tax fix -- full
    # collections stop re-tracing codecs/maps/config every pause)
    from ceph_tpu.utils import gcopt

    gcopt.freeze_after_warmup()
    stop = asyncio.Event()
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if reporter is not None:
        reporter.stop()
    if asok is not None:
        await asok.stop()
    await messenger.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--id", type=int, required=True)
    ap.add_argument("--addr-map", required=True)
    ap.add_argument("--objectstore", default="memstore")
    ap.add_argument("--data-path", default="")
    ap.add_argument("--op-queue", default="wpq")
    ap.add_argument("--keyring", default="",
                    help="keyring file enabling cephx-style auth")
    ap.add_argument("--admin-socket", default="",
                    help="unix socket path for daemon introspection "
                         "(default <data-path>/<name>.asok)")
    ap.add_argument("--cluster-conf", default="",
                    help="cluster.json with the pool profile: this OSD "
                         "hosts a primary engine for the pool")
    args = ap.parse_args(argv)
    try:
        asyncio.run(serve(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
