"""Daemon entry points (reference: src/ceph_osd.cc etc. -- one process
per daemon, booted by vstart-style scripts)."""
