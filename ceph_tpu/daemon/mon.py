"""ceph-mon daemon: one monitor process over TCP with a durable store.

Reference boot flow: src/ceph_mon.cc -- global init, open the
MonitorDBStore, messenger, Monitor::preinit/bootstrap into an election.
Here:

  python -m ceph_tpu.daemon.mon --rank R --mons N --addr-map map.json \
      [--store-path DIR] [--admin-socket PATH]

``map.json`` must name every monitor (``mon.0``..``mon.N-1``).  The
process prints ``mon.R up`` once the socket listens.  Rank 0 kicks the
first election after a short settle delay; every rank runs the lease
tick, so the quorum re-elects across real process kills and restarts,
and a mon restarted on its store rejoins with its committed state (the
paxos share path catches it up on anything it missed).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys


async def serve(args) -> None:
    from ceph_tpu.mon.monitor import Monitor
    from ceph_tpu.msg.tcp import TCPMessenger

    from ceph_tpu.utils import aio

    addr_map = {
        k: tuple(v)
        for k, v in (await aio.read_json(args.addr_map)).items()
    }
    name = f"mon.{args.rank}"
    keyring = None
    if args.keyring:
        from ceph_tpu.auth import KeyRing

        keyring = KeyRing.load(args.keyring)
    messenger = TCPMessenger(name, addr_map, keyring=keyring)
    await messenger.start()
    mon = Monitor(args.rank, args.mons, messenger,
                  store_path=args.store_path or None)
    asok = None
    if args.admin_socket:
        from ceph_tpu.utils.admin_socket import AdminSocket

        asok = AdminSocket(args.admin_socket)
        asok.register("mon_status", lambda cmd: {
            "name": name,
            "rank": mon.rank,
            "state": "leader" if mon.is_leader() else
                     ("peon" if mon.leader is not None else "probing"),
            "quorum": mon.quorum,
            "election_epoch": mon.election_epoch,
            "osdmap_epoch": mon.osdmap.epoch,
            "paxos_last_committed": mon.paxos.store.last_committed,
        })
        await asok.start()
    print(f"{name} up", flush=True)
    # lease tick: peons probe the leader and call an election on
    # silence (Monitor.start_tick), so a killed leader is replaced
    mon.start_tick(interval=0.25)

    # mgr telemetry: mons beacon + report like every daemon (MON_DOWN
    # derives from beacon staleness; the lag probe attributes a wedged
    # mon event loop).  Report payload is the mon's own state summary.
    from ceph_tpu.mgr.report import ReportSender, mgr_targets_from
    from ceph_tpu.mgr.report import REPORT_SCHEMA_VERSION

    reporter = None
    mgr_targets = mgr_targets_from(addr_map)
    if mgr_targets:
        def mon_stats():
            return {
                "v": REPORT_SCHEMA_VERSION,
                "kind": "mon",
                "rank": mon.rank,
                "is_leader": mon.is_leader(),
                "election_epoch": mon.election_epoch,
                "osdmap_epoch": mon.osdmap.epoch,
                "perf": {},
            }

        reporter = ReportSender(name, messenger, mon_stats, mgr_targets)
        reporter.start()

    async def bootstrap():
        # every rank proposes until SOME leader is known, staggered so
        # the lowest live rank usually wins first (Elector probing): a
        # late-booting or restarted mon thereby forces a round it can
        # learn the leader from, instead of waiting forever
        await asyncio.sleep(args.settle + args.rank * 0.3)
        while mon.leader is None:
            await mon.start_election()
            await asyncio.sleep(0.5 + args.rank * 0.2)

    messenger.adopt_task(
        f"{name}.bootstrap",
        asyncio.get_event_loop().create_task(bootstrap()))

    # startup warm-up is over: freeze the boot heap out of the
    # collector (gc_freeze_on_start; the r19 gc-pause-tax fix)
    from ceph_tpu.utils import gcopt

    gcopt.freeze_after_warmup()
    stop = asyncio.get_event_loop().create_future()

    def _stop(*_a):
        if not stop.done():
            stop.set_result(True)

    loop = asyncio.get_event_loop()
    loop.add_signal_handler(signal.SIGTERM, _stop)
    loop.add_signal_handler(signal.SIGINT, _stop)
    await stop
    if reporter is not None:
        reporter.stop()
    if asok is not None:
        await asok.stop()
    await messenger.shutdown()
    mon.close_store()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--mons", type=int, required=True)
    ap.add_argument("--addr-map", required=True)
    ap.add_argument("--store-path", default="")
    ap.add_argument("--keyring", default="",
                    help="keyring enabling cephx-style auth; entities "
                         "minted later via `auth get-or-create` are "
                         "learned from the replicated AuthDB")
    ap.add_argument("--admin-socket", default="")
    ap.add_argument("--settle", type=float, default=0.5,
                    help="seconds rank 0 waits before the first election")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    asyncio.new_event_loop().run_until_complete(serve(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
