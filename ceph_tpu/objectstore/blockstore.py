"""BlockStore: raw-block backend -- the BlueStore-analogue engine.

Reference: src/os/bluestore/BlueStore.cc (role, not design): data lives on
a raw block "device" (a flat file) carved into fixed allocation units;
ALL metadata -- onodes (size, xattrs, extent map), omap, deferred-write
records -- lives in the LSM KeyValueDB (ceph_tpu/kv/lsm.py), whose WAL
makes every ObjectStore transaction atomic (the one-RocksDB-WriteBatch
contract, BlueStore::queue_transactions).

Write strategy (BlueStore's two paths, simplified to allocation-unit
granularity):

* **COW big writes**: new/changed units are written to FRESHLY allocated
  units *before* the KV commit references them, so a crash mid-write
  leaves the old onode pointing at intact old data (BlueStore's
  write-new-blob path).
* **Deferred small overwrites**: a sub-threshold overwrite of an already
  allocated unit rides INSIDE the KV transaction as a deferred record
  (phys offset + bytes), is applied in place after the commit, and is
  replayed idempotently at mount -- BlueStore's deferred-write WAL
  (bluestore_prefer_deferred_size).

The allocator is an in-memory free-set rebuilt at mount by scanning
onode extent maps + pending deferred records -- BlueStore's
NCB/allocation-from-onodes recovery mode rather than a persisted
freelist.

Compression (BlueStore blob compression, src/os/bluestore/BlueStore.cc
_do_write_data compress path): big writes covering >= 2 full allocation
units may be stored as one compressed blob -- fewer physical units than
the logical span, a crc32 over the compressed payload (the blob csum
role), and the onode extent map pointing at the blob.  A partial
overwrite of a compressed span first decompresses it back to plain
units (BlueStore's blob rewrite on overlap); reads verify the csum and
raise EIO-style on mismatch.

KV prefixes: "O" onodes, "M" omap ("<oid>\\x00<key>"), "D" deferred
records keyed by monotonic sequence.
"""

from __future__ import annotations

import os
import zlib as _zlib
from typing import Dict, List, Optional

from ceph_tpu import compressor as compressor_mod
from ceph_tpu.kv import lsm as lsm_mod
from ceph_tpu.kv.keyvaluedb import KVTransaction
from ceph_tpu.objectstore.statfs import ScanStatsMixin
from ceph_tpu.osd.types import Transaction
from ceph_tpu.utils.encoding import Decoder, Encoder


class BlockStore(ScanStatsMixin):
    def __init__(self, path: str, alloc_unit: int = 64 * 1024,
                 deferred_threshold: int = 32 * 1024,
                 compression: Optional[str] = None):
        if not path:
            raise ValueError("blockstore needs a data path")
        os.makedirs(path, exist_ok=True)
        self.alloc_unit = alloc_unit
        self.deferred_threshold = min(deferred_threshold, alloc_unit)
        self._comp = (compressor_mod.create(compression)
                      if compression and compression != "none" else None)
        self.db = lsm_mod.LSMStore(os.path.join(path, "kv"))
        self.db.open()
        self.block_path = os.path.join(path, "block")
        if not os.path.exists(self.block_path):
            with open(self.block_path, "wb"):
                pass
        self._dev = open(self.block_path, "r+b")
        self._free: set = set()
        self._high_water = 0
        self._deferred_seq = 0
        self._onode_cache: Dict[str, dict] = {}
        self._mount()

    # -- mount / crash recovery -------------------------------------------

    def _mount(self) -> None:
        """Replay deferred writes, rebuild the allocator from onodes."""
        used = set()
        for oid, raw in self.db.get_iterator("O"):
            onode = Decoder(raw).value()
            used.update(onode["extents"].values())
            for blob in onode.get("cblobs", {}).values():
                used.update(blob["phys"])
        replayed = KVTransaction()
        n_deferred = 0
        for seq, raw in self.db.get_iterator("D"):
            rec = Decoder(raw).value()
            # idempotent in-place replay (BlueStore deferred replay)
            self._dev_write(rec["pofs"], rec["data"])
            replayed.rmkey("D", seq)
            n_deferred += 1
            self._deferred_seq = max(self._deferred_seq, int(seq) + 1)
        if n_deferred:
            self._dev.flush()
            self.db.submit_transaction(replayed)
        self._high_water = (max(used) + 1) if used else 0
        self._free = set(range(self._high_water)) - used

    def umount(self) -> None:
        self.db.close()
        self._dev.close()

    # -- device helpers ----------------------------------------------------

    def _dev_write(self, pofs: int, data: bytes) -> None:
        self._dev.seek(pofs)
        self._dev.write(data)

    def _dev_read(self, unit: int) -> bytes:
        self._dev.seek(unit * self.alloc_unit)
        buf = self._dev.read(self.alloc_unit)
        return buf.ljust(self.alloc_unit, b"\x00")

    def _alloc(self) -> int:
        if self._free:
            u = min(self._free)
            self._free.discard(u)
            return u
        u = self._high_water
        self._high_water += 1
        return u

    # -- onode helpers -----------------------------------------------------

    def _get_onode(self, oid: str) -> Optional[dict]:
        if oid in self._onode_cache:
            return self._onode_cache[oid]
        raw = self.db.get("O", oid)
        if raw is None:
            return None
        onode = Decoder(raw).value()
        # extent keys round-trip as strings; normalize to int logical units
        onode["extents"] = {int(k): v for k, v in onode["extents"].items()}
        onode["cblobs"] = {int(k): v for k, v in
                           onode.get("cblobs", {}).items()}
        self._onode_cache[oid] = onode
        return onode

    @staticmethod
    def _onode_bytes(onode: dict) -> bytes:
        enc = dict(onode)
        enc["extents"] = {str(k): v for k, v in onode["extents"].items()}
        enc["cblobs"] = {str(k): v for k, v in
                         onode.get("cblobs", {}).items()}
        return Encoder().value(enc).bytes()

    # -- compressed blobs (BlueStore blob compression) ---------------------

    def _read_blob(self, blob: dict) -> bytes:
        """Reassemble + verify + decompress one blob; csum failure is
        the EIO the scrub path expects from a bad device."""
        comp = b"".join(self._dev_read(p) for p in blob["phys"])
        comp = comp[: blob["clen"]]
        if _zlib.crc32(comp) != blob["csum"]:
            raise IOError(
                f"compressed blob csum mismatch (span {blob['span']})")
        return compressor_mod.create(blob["alg"]).decompress(comp)

    # -- transaction path --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        """Stage data writes (COW units to the device first), then one
        atomic KV batch carrying onodes + omap + deferred records, then
        apply deferred in-place writes."""
        batch = KVTransaction()
        onodes: Dict[str, Optional[dict]] = {}
        deferred: List[dict] = []
        freed: List[int] = []

        def onode_for(oid: str) -> dict:
            if oid in onodes and onodes[oid] is not None:
                return onodes[oid]  # type: ignore[return-value]
            cur = None if onodes.get(oid, "?") is None else self._get_onode(oid)
            if cur is None:
                cur = {"size": 0, "attrs": {}, "extents": {}, "cblobs": {}}
            else:
                cur = {"size": cur["size"], "attrs": dict(cur["attrs"]),
                       "extents": dict(cur["extents"]),
                       "cblobs": {k: dict(v) for k, v in
                                  cur.get("cblobs", {}).items()}}
            onodes[oid] = cur
            return cur

        def explode_blobs(onode: dict, u_lo: int, u_hi: int) -> None:
            """Rewrite compressed blobs overlapping logical units
            [u_lo, u_hi] as plain COW units (BlueStore decompresses and
            rewrites a blob a write lands inside)."""
            au = self.alloc_unit
            for b0 in sorted(onode["cblobs"]):
                blob = onode["cblobs"][b0]
                if b0 > u_hi or b0 + blob["span"] - 1 < u_lo:
                    continue
                data = self._read_blob(blob)
                del onode["cblobs"][b0]
                freed.extend(blob["phys"])
                for i in range(blob["span"]):
                    new_phys = self._alloc()
                    self._dev_write(
                        new_phys * au,
                        data[i * au:(i + 1) * au].ljust(au, b"\x00"))
                    onode["extents"][b0 + i] = new_phys

        def write_units(onode: dict, offset: int, data: bytes) -> None:
            au = self.alloc_unit
            end = offset + len(data)
            u0, u1 = offset // au, (end - 1) // au
            explode_blobs(onode, u0, u1)
            if self._comp is not None:
                # blob compression for the aligned full-unit core of a
                # big write: stored only when it saves whole units
                core_lo = (offset + au - 1) // au
                core_hi = end // au
                n = core_hi - core_lo
                if n >= 2:
                    span = data[core_lo * au - offset:core_hi * au - offset]
                    comp = self._comp.compress(span)
                    units_needed = (len(comp) + au - 1) // au
                    if units_needed < n:
                        phys = []
                        for i in range(units_needed):
                            p = self._alloc()
                            self._dev_write(
                                p * au,
                                comp[i * au:(i + 1) * au].ljust(au, b"\0"))
                            phys.append(p)
                        for u in range(core_lo, core_hi):
                            old = onode["extents"].pop(u, None)
                            if old is not None:
                                freed.append(old)
                        onode["cblobs"][core_lo] = {
                            "phys": phys, "span": n, "clen": len(comp),
                            "alg": self._comp.name,
                            "csum": _zlib.crc32(comp),
                        }
                        # head/tail partial pieces go the plain path
                        if offset < core_lo * au:
                            write_units(onode, offset,
                                        data[: core_lo * au - offset])
                        if core_hi * au < end:
                            write_units(onode, core_hi * au,
                                        data[core_hi * au - offset:])
                        return
            for u in range(u0, u1 + 1):
                lo = max(offset, u * au)
                hi = min(end, (u + 1) * au)
                piece = data[lo - offset:hi - offset]
                old_phys = onode["extents"].get(u)
                full_unit = (lo == u * au and hi == (u + 1) * au)
                if (
                    old_phys is not None and not full_unit
                    and len(piece) <= self.deferred_threshold
                ):
                    # deferred small overwrite: bytes ride the KV commit
                    deferred.append({
                        "pofs": old_phys * au + (lo - u * au),
                        "data": piece,
                    })
                    continue
                # COW: merge with old unit content (zeros for holes),
                # write to a freshly allocated unit
                if full_unit:
                    buf = piece
                else:
                    base = (
                        bytearray(self._dev_read(old_phys))
                        if old_phys is not None
                        else bytearray(au)
                    )
                    if old_phys is not None:
                        # earlier ops in THIS txn may have staged deferred
                        # pieces against this unit that are not on the
                        # device yet: fold them into the merge base
                        p0 = old_phys * au
                        for rec in deferred:
                            if p0 <= rec["pofs"] < p0 + au:
                                off = rec["pofs"] - p0
                                base[off:off + len(rec["data"])] = rec["data"]
                    base[lo - u * au:hi - u * au] = piece
                    buf = bytes(base)
                new_phys = self._alloc()
                self._dev_write(new_phys * au, buf)
                onode["extents"][u] = new_phys
                if old_phys is not None:
                    freed.append(old_phys)

        def truncate_to(onode: dict, size: int) -> None:
            au = self.alloc_unit
            old_size = onode["size"]
            if size < old_size:
                keep_units = (size + au - 1) // au if size else 0
                for b0 in sorted(onode["cblobs"]):
                    blob = onode["cblobs"][b0]
                    if b0 >= keep_units:
                        freed.extend(blob["phys"])
                        del onode["cblobs"][b0]
                    elif size < (b0 + blob["span"]) * au:
                        # the cut lands inside the blob (incl. inside
                        # its LAST unit): back to plain units so the
                        # tail logic below can zero/free them
                        explode_blobs(onode, b0, b0 + blob["span"] - 1)
                for u in list(onode["extents"]):
                    if u >= keep_units:
                        freed.append(onode["extents"].pop(u))
                # zero the stale tail of the last kept unit via COW so a
                # later re-grow reads zeros there
                if size % au and (size // au) in onode["extents"]:
                    u = size // au
                    base = bytearray(self._dev_read(onode["extents"][u]))
                    base[size % au:] = bytes(au - size % au)
                    new_phys = self._alloc()
                    self._dev_write(new_phys * au, bytes(base))
                    freed.append(onode["extents"][u])
                    onode["extents"][u] = new_phys
            onode["size"] = size

        for op in txn.ops:
            if op.op == "write":
                onode = onode_for(op.oid)
                write_units(onode, op.offset, op.data)
                onode["size"] = max(onode["size"], op.offset + len(op.data))
            elif op.op == "truncate":
                truncate_to(onode_for(op.oid), op.offset)
            elif op.op == "setattr":
                onode_for(op.oid)["attrs"][op.attr_name] = op.attr_value
            elif op.op == "clone":
                src_exists = (
                    onodes.get(op.oid) is not None
                    if op.oid in onodes else self._get_onode(op.oid)
                )
                if not src_exists:
                    raise FileNotFoundError(op.oid)
                src = onode_for(op.oid)
                au = self.alloc_unit
                dst = {"size": src["size"], "attrs": dict(src["attrs"]),
                       "extents": {}, "cblobs": {}}
                for u, phys in src["extents"].items():
                    base = bytearray(self._dev_read(phys))
                    p0 = phys * au
                    for rec in deferred:
                        if p0 <= rec["pofs"] < p0 + au:
                            off = rec["pofs"] - p0
                            base[off:off + len(rec["data"])] = rec["data"]
                    new_phys = self._alloc()
                    self._dev_write(new_phys * au, bytes(base))
                    dst["extents"][u] = new_phys
                for b0, blob in src["cblobs"].items():
                    phys = []
                    for p in blob["phys"]:
                        np_ = self._alloc()
                        self._dev_write(np_ * au, self._dev_read(p))
                        phys.append(np_)
                    dst["cblobs"][b0] = dict(blob, phys=phys)
                # a clone earlier staged under this name is replaced
                old = onodes.get(op.attr_name)
                if old:
                    freed.extend(old["extents"].values())
                    for blob in old["cblobs"].values():
                        freed.extend(blob["phys"])
                onodes[op.attr_name] = dst
            elif op.op == "remove":
                cur = onode_for(op.oid)
                freed.extend(cur["extents"].values())
                for blob in cur["cblobs"].values():
                    freed.extend(blob["phys"])
                onodes[op.oid] = None
                for k in self._omap_db_keys(op.oid):
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            elif op.op == "omap_set":
                onode_for(op.oid)  # touch/create like the other stores
                for k, v in op.attr_value.items():
                    batch.set("M", f"{op.oid}\x00{k}", v)
            elif op.op == "omap_rm":
                onode_for(op.oid)
                for k in op.attr_value:
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            elif op.op == "omap_clear":
                onode_for(op.oid)
                for k in self._omap_db_keys(op.oid):
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            else:
                raise ValueError(f"unknown txn op {op.op!r}")

        # data first, then the metadata commit that references it
        self._dev.flush()
        for oid, onode in onodes.items():
            if onode is None:
                batch.rmkey("O", oid)
                self._onode_cache.pop(oid, None)
            else:
                batch.set("O", oid, self._onode_bytes(onode))
                self._onode_cache[oid] = onode
        cleanup = KVTransaction()
        for rec in deferred:
            key = f"{self._deferred_seq:016d}"
            self._deferred_seq += 1
            batch.set("D", key, Encoder().value(rec).bytes())
            cleanup.rmkey("D", key)
        self.db.submit_transaction(batch)
        # deferred applies land in place only after their records are
        # durable; a crash between is covered by mount-time replay
        if deferred:
            for rec in deferred:
                self._dev_write(rec["pofs"], rec["data"])
            self._dev.flush()
            self.db.submit_transaction(cleanup)
        self._free.update(freed)
        self._stats_invalidate()

    # -- reads -------------------------------------------------------------

    def read(self, oid: str, offset: int = 0, length: int = -1) -> bytes:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        size = onode["size"]
        if length < 0:
            length = max(0, size - offset)
        end = min(offset + length, size)
        if end <= offset:
            return b""
        au = self.alloc_unit
        out = bytearray(end - offset)
        for u in range(offset // au, (end - 1) // au + 1):
            phys = onode["extents"].get(u)
            if phys is None:
                continue  # hole or compressed blob (filled below)
            unit = self._dev_read(phys)
            lo = max(offset, u * au)
            hi = min(end, (u + 1) * au)
            out[lo - offset:hi - offset] = unit[lo - u * au:hi - u * au]
        for b0, blob in onode.get("cblobs", {}).items():
            blo, bhi = b0 * au, (b0 + blob["span"]) * au
            if bhi <= offset or blo >= end:
                continue
            data = self._read_blob(blob)  # one decompress per blob
            lo = max(offset, blo)
            hi = min(end, bhi)
            out[lo - offset:hi - offset] = \
                data[lo - blo:hi - blo].ljust(hi - lo, b"\x00")
        return bytes(out)

    def getattr(self, oid: str, name: str):
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        return onode["attrs"].get(name)

    def _omap_db_keys(self, oid: str) -> List[str]:
        prefix = oid + "\x00"
        return [
            k[len(prefix):]
            for k, _ in self.db.get_iterator("M")
            if k.startswith(prefix)
        ]

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        if self._get_onode(oid) is None:
            raise FileNotFoundError(oid)
        out = {}
        prefix = oid + "\x00"
        for k, v in self.db.get_iterator("M"):
            if k.startswith(prefix):
                name = k[len(prefix):]
                if keys is None or name in keys:
                    out[name] = v
        return out

    def stat(self, oid: str) -> int:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        return onode["size"]

    def exists(self, oid: str) -> bool:
        return self._get_onode(oid) is not None

    def list_objects(self) -> List[str]:
        return sorted(k for k, _ in self.db.get_iterator("O"))

    # -- fault injection (store_test corrupt hook) -------------------------

    def corrupt(self, oid: str, offset: int) -> None:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        au = self.alloc_unit
        u = offset // au
        phys = onode["extents"].get(u)
        if phys is None:
            # the unit may live in a compressed blob: flip a payload
            # byte so the blob csum (and hence the read) fails -- the
            # EIO surface scrub repairs from
            for b0, blob in onode.get("cblobs", {}).items():
                if b0 <= u < b0 + blob["span"]:
                    phys = blob["phys"][0]
                    break
            if phys is None:
                return
            pofs = phys * au
        else:
            pofs = phys * au + offset % au
        self._dev.seek(pofs)
        b = self._dev.read(1)
        self._dev.seek(pofs)
        self._dev.write(bytes([b[0] ^ 0xFF]))
        self._dev.flush()
