"""BlockStore: raw-block backend -- the BlueStore-analogue engine.

Reference: src/os/bluestore/BlueStore.cc (role, not design): data lives on
a raw block "device" (a flat file) carved into fixed allocation units;
ALL metadata -- onodes (size, xattrs, extent map), omap, deferred-write
records -- lives in the LSM KeyValueDB (ceph_tpu/kv/lsm.py), whose WAL
makes every ObjectStore transaction atomic (the one-RocksDB-WriteBatch
contract, BlueStore::queue_transactions).

Write strategy (BlueStore's two paths, simplified to allocation-unit
granularity):

* **COW big writes**: new/changed units are written to FRESHLY allocated
  units *before* the KV commit references them, so a crash mid-write
  leaves the old onode pointing at intact old data (BlueStore's
  write-new-blob path).
* **Deferred small overwrites**: a sub-threshold overwrite of an already
  allocated unit rides INSIDE the KV transaction as a deferred record
  (phys offset + bytes), is applied in place after the commit, and is
  replayed idempotently at mount -- BlueStore's deferred-write WAL
  (bluestore_prefer_deferred_size).

The allocator is an in-memory free-set rebuilt at mount by scanning
onode extent maps + pending deferred records -- BlueStore's
NCB/allocation-from-onodes recovery mode rather than a persisted
freelist.

KV prefixes: "O" onodes, "M" omap ("<oid>\\x00<key>"), "D" deferred
records keyed by monotonic sequence.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ceph_tpu.kv import lsm as lsm_mod
from ceph_tpu.kv.keyvaluedb import KVTransaction
from ceph_tpu.osd.types import Transaction
from ceph_tpu.utils.encoding import Decoder, Encoder


class BlockStore:
    def __init__(self, path: str, alloc_unit: int = 64 * 1024,
                 deferred_threshold: int = 32 * 1024):
        if not path:
            raise ValueError("blockstore needs a data path")
        os.makedirs(path, exist_ok=True)
        self.alloc_unit = alloc_unit
        self.deferred_threshold = min(deferred_threshold, alloc_unit)
        self.db = lsm_mod.LSMStore(os.path.join(path, "kv"))
        self.db.open()
        self.block_path = os.path.join(path, "block")
        if not os.path.exists(self.block_path):
            with open(self.block_path, "wb"):
                pass
        self._dev = open(self.block_path, "r+b")
        self._free: set = set()
        self._high_water = 0
        self._deferred_seq = 0
        self._onode_cache: Dict[str, dict] = {}
        self._mount()

    # -- mount / crash recovery -------------------------------------------

    def _mount(self) -> None:
        """Replay deferred writes, rebuild the allocator from onodes."""
        used = set()
        for oid, raw in self.db.get_iterator("O"):
            onode = Decoder(raw).value()
            used.update(onode["extents"].values())
        replayed = KVTransaction()
        n_deferred = 0
        for seq, raw in self.db.get_iterator("D"):
            rec = Decoder(raw).value()
            # idempotent in-place replay (BlueStore deferred replay)
            self._dev_write(rec["pofs"], rec["data"])
            replayed.rmkey("D", seq)
            n_deferred += 1
            self._deferred_seq = max(self._deferred_seq, int(seq) + 1)
        if n_deferred:
            self._dev.flush()
            self.db.submit_transaction(replayed)
        self._high_water = (max(used) + 1) if used else 0
        self._free = set(range(self._high_water)) - used

    def umount(self) -> None:
        self.db.close()
        self._dev.close()

    # -- device helpers ----------------------------------------------------

    def _dev_write(self, pofs: int, data: bytes) -> None:
        self._dev.seek(pofs)
        self._dev.write(data)

    def _dev_read(self, unit: int) -> bytes:
        self._dev.seek(unit * self.alloc_unit)
        buf = self._dev.read(self.alloc_unit)
        return buf.ljust(self.alloc_unit, b"\x00")

    def _alloc(self) -> int:
        if self._free:
            u = min(self._free)
            self._free.discard(u)
            return u
        u = self._high_water
        self._high_water += 1
        return u

    # -- onode helpers -----------------------------------------------------

    def _get_onode(self, oid: str) -> Optional[dict]:
        if oid in self._onode_cache:
            return self._onode_cache[oid]
        raw = self.db.get("O", oid)
        if raw is None:
            return None
        onode = Decoder(raw).value()
        # extent keys round-trip as strings; normalize to int logical units
        onode["extents"] = {int(k): v for k, v in onode["extents"].items()}
        self._onode_cache[oid] = onode
        return onode

    @staticmethod
    def _onode_bytes(onode: dict) -> bytes:
        enc = dict(onode)
        enc["extents"] = {str(k): v for k, v in onode["extents"].items()}
        return Encoder().value(enc).bytes()

    # -- transaction path --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        """Stage data writes (COW units to the device first), then one
        atomic KV batch carrying onodes + omap + deferred records, then
        apply deferred in-place writes."""
        batch = KVTransaction()
        onodes: Dict[str, Optional[dict]] = {}
        deferred: List[dict] = []
        freed: List[int] = []

        def onode_for(oid: str) -> dict:
            if oid in onodes and onodes[oid] is not None:
                return onodes[oid]  # type: ignore[return-value]
            cur = None if onodes.get(oid, "?") is None else self._get_onode(oid)
            if cur is None:
                cur = {"size": 0, "attrs": {}, "extents": {}}
            else:
                cur = {"size": cur["size"], "attrs": dict(cur["attrs"]),
                       "extents": dict(cur["extents"])}
            onodes[oid] = cur
            return cur

        def write_units(onode: dict, offset: int, data: bytes) -> None:
            au = self.alloc_unit
            end = offset + len(data)
            u0, u1 = offset // au, (end - 1) // au
            for u in range(u0, u1 + 1):
                lo = max(offset, u * au)
                hi = min(end, (u + 1) * au)
                piece = data[lo - offset:hi - offset]
                old_phys = onode["extents"].get(u)
                full_unit = (lo == u * au and hi == (u + 1) * au)
                if (
                    old_phys is not None and not full_unit
                    and len(piece) <= self.deferred_threshold
                ):
                    # deferred small overwrite: bytes ride the KV commit
                    deferred.append({
                        "pofs": old_phys * au + (lo - u * au),
                        "data": piece,
                    })
                    continue
                # COW: merge with old unit content (zeros for holes),
                # write to a freshly allocated unit
                if full_unit:
                    buf = piece
                else:
                    base = (
                        bytearray(self._dev_read(old_phys))
                        if old_phys is not None
                        else bytearray(au)
                    )
                    if old_phys is not None:
                        # earlier ops in THIS txn may have staged deferred
                        # pieces against this unit that are not on the
                        # device yet: fold them into the merge base
                        p0 = old_phys * au
                        for rec in deferred:
                            if p0 <= rec["pofs"] < p0 + au:
                                off = rec["pofs"] - p0
                                base[off:off + len(rec["data"])] = rec["data"]
                    base[lo - u * au:hi - u * au] = piece
                    buf = bytes(base)
                new_phys = self._alloc()
                self._dev_write(new_phys * au, buf)
                onode["extents"][u] = new_phys
                if old_phys is not None:
                    freed.append(old_phys)

        def truncate_to(onode: dict, size: int) -> None:
            au = self.alloc_unit
            old_size = onode["size"]
            if size < old_size:
                keep_units = (size + au - 1) // au if size else 0
                for u in list(onode["extents"]):
                    if u >= keep_units:
                        freed.append(onode["extents"].pop(u))
                # zero the stale tail of the last kept unit via COW so a
                # later re-grow reads zeros there
                if size % au and (size // au) in onode["extents"]:
                    u = size // au
                    base = bytearray(self._dev_read(onode["extents"][u]))
                    base[size % au:] = bytes(au - size % au)
                    new_phys = self._alloc()
                    self._dev_write(new_phys * au, bytes(base))
                    freed.append(onode["extents"][u])
                    onode["extents"][u] = new_phys
            onode["size"] = size

        for op in txn.ops:
            if op.op == "write":
                onode = onode_for(op.oid)
                write_units(onode, op.offset, op.data)
                onode["size"] = max(onode["size"], op.offset + len(op.data))
            elif op.op == "truncate":
                truncate_to(onode_for(op.oid), op.offset)
            elif op.op == "setattr":
                onode_for(op.oid)["attrs"][op.attr_name] = op.attr_value
            elif op.op == "clone":
                src_exists = (
                    onodes.get(op.oid) is not None
                    if op.oid in onodes else self._get_onode(op.oid)
                )
                if not src_exists:
                    raise FileNotFoundError(op.oid)
                src = onode_for(op.oid)
                au = self.alloc_unit
                dst = {"size": src["size"], "attrs": dict(src["attrs"]),
                       "extents": {}}
                for u, phys in src["extents"].items():
                    base = bytearray(self._dev_read(phys))
                    p0 = phys * au
                    for rec in deferred:
                        if p0 <= rec["pofs"] < p0 + au:
                            off = rec["pofs"] - p0
                            base[off:off + len(rec["data"])] = rec["data"]
                    new_phys = self._alloc()
                    self._dev_write(new_phys * au, bytes(base))
                    dst["extents"][u] = new_phys
                # a clone earlier staged under this name is replaced
                old = onodes.get(op.attr_name)
                if old:
                    freed.extend(old["extents"].values())
                onodes[op.attr_name] = dst
            elif op.op == "remove":
                cur = onode_for(op.oid)
                freed.extend(cur["extents"].values())
                onodes[op.oid] = None
                for k in self._omap_db_keys(op.oid):
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            elif op.op == "omap_set":
                onode_for(op.oid)  # touch/create like the other stores
                for k, v in op.attr_value.items():
                    batch.set("M", f"{op.oid}\x00{k}", v)
            elif op.op == "omap_rm":
                onode_for(op.oid)
                for k in op.attr_value:
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            elif op.op == "omap_clear":
                onode_for(op.oid)
                for k in self._omap_db_keys(op.oid):
                    batch.rmkey("M", f"{op.oid}\x00{k}")
            else:
                raise ValueError(f"unknown txn op {op.op!r}")

        # data first, then the metadata commit that references it
        self._dev.flush()
        for oid, onode in onodes.items():
            if onode is None:
                batch.rmkey("O", oid)
                self._onode_cache.pop(oid, None)
            else:
                batch.set("O", oid, self._onode_bytes(onode))
                self._onode_cache[oid] = onode
        cleanup = KVTransaction()
        for rec in deferred:
            key = f"{self._deferred_seq:016d}"
            self._deferred_seq += 1
            batch.set("D", key, Encoder().value(rec).bytes())
            cleanup.rmkey("D", key)
        self.db.submit_transaction(batch)
        # deferred applies land in place only after their records are
        # durable; a crash between is covered by mount-time replay
        if deferred:
            for rec in deferred:
                self._dev_write(rec["pofs"], rec["data"])
            self._dev.flush()
            self.db.submit_transaction(cleanup)
        self._free.update(freed)

    # -- reads -------------------------------------------------------------

    def read(self, oid: str, offset: int = 0, length: int = -1) -> bytes:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        size = onode["size"]
        if length < 0:
            length = max(0, size - offset)
        end = min(offset + length, size)
        if end <= offset:
            return b""
        au = self.alloc_unit
        out = bytearray(end - offset)
        for u in range(offset // au, (end - 1) // au + 1):
            phys = onode["extents"].get(u)
            if phys is None:
                continue  # hole: zeros
            unit = self._dev_read(phys)
            lo = max(offset, u * au)
            hi = min(end, (u + 1) * au)
            out[lo - offset:hi - offset] = unit[lo - u * au:hi - u * au]
        return bytes(out)

    def getattr(self, oid: str, name: str):
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        return onode["attrs"].get(name)

    def _omap_db_keys(self, oid: str) -> List[str]:
        prefix = oid + "\x00"
        return [
            k[len(prefix):]
            for k, _ in self.db.get_iterator("M")
            if k.startswith(prefix)
        ]

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        if self._get_onode(oid) is None:
            raise FileNotFoundError(oid)
        out = {}
        prefix = oid + "\x00"
        for k, v in self.db.get_iterator("M"):
            if k.startswith(prefix):
                name = k[len(prefix):]
                if keys is None or name in keys:
                    out[name] = v
        return out

    def stat(self, oid: str) -> int:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        return onode["size"]

    def exists(self, oid: str) -> bool:
        return self._get_onode(oid) is not None

    def list_objects(self) -> List[str]:
        return sorted(k for k, _ in self.db.get_iterator("O"))

    # -- fault injection (store_test corrupt hook) -------------------------

    def corrupt(self, oid: str, offset: int) -> None:
        onode = self._get_onode(oid)
        if onode is None:
            raise FileNotFoundError(oid)
        au = self.alloc_unit
        phys = onode["extents"].get(offset // au)
        if phys is None:
            return
        pofs = phys * au + offset % au
        self._dev.seek(pofs)
        b = self._dev.read(1)
        self._dev.seek(pofs)
        self._dev.write(bytes([b[0] ^ 0xFF]))
        self._dev.flush()
