"""File-backed ObjectStore with a write-ahead journal (FileStore-lite).

Reference: src/os/filestore/FileStore.cc (6050 LoC) + FileJournal -- object
data lives in ordinary files, every transaction is journaled first
(write-ahead), then applied to the filesystem; on mount the journal is
replayed past the last committed sequence.  Same contract here:

* ``queue_transaction``: encode the transaction, append one crc-framed
  record ``(seq, txn)`` to ``journal``, fsync, then apply to files;
* a ``COMMITTED`` marker file records the last applied seq (written
  atomically via rename after each apply -- the reference's
  ``commit_op_seq``); on mount, journal records with seq > committed are
  re-applied (apply is idempotent), torn tails are discarded;
* the journal is truncated once it exceeds ``journal_trim_bytes``
  (sync + trim, reference FileStore::sync_entry).

Objects are files named by an escaped oid under ``path/objects/``; xattrs
live in one sidecar KV file per object dir chunk -- kept simple: a single
``attrs`` LSM-free framed dict per object alongside the data file.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ceph_tpu.objectstore.statfs import ScanStatsMixin
from ceph_tpu.osd.types import Transaction, TxnOp
from ceph_tpu.utils.encoding import Decoder, Encoder, frame, unframe


def _escape(oid: str) -> str:
    """Filesystem-safe object name (reference LFNIndex escaping role)."""
    out = []
    for ch in oid:
        if ch.isalnum() or ch in "._-":
            out.append(ch)
        else:
            out.append(f"%{ord(ch):02x}")
    return "".join(out)


def _unescape(name: str) -> str:
    out = []
    i = 0
    while i < len(name):
        if name[i] == "%":
            out.append(chr(int(name[i + 1 : i + 3], 16)))
            i += 3
        else:
            out.append(name[i])
            i += 1
    return "".join(out)


def _encode_txn(seq: int, txn: Transaction) -> bytes:
    enc = Encoder()
    enc.u64(seq)
    enc.varint(len(txn.ops))
    for op in txn.ops:
        enc.string(op.op).string(op.oid).varint(op.offset)
        enc.blob(op.data)
        enc.string(op.attr_name)
        enc.value(op.attr_value)
    return enc.bytes()


def _decode_txn(payload: bytes):
    dec = Decoder(payload)
    seq = dec.u64()
    txn = Transaction()
    for _ in range(dec.varint()):
        op = dec.string()
        oid = dec.string()
        offset = dec.varint()
        data = dec.blob()
        attr_name = dec.string()
        attr_value = dec.value()
        txn.ops.append(
            TxnOp(op, oid=oid, offset=offset, data=data,
                  attr_name=attr_name, attr_value=attr_value)
        )
    return seq, txn


class FileStore(ScanStatsMixin):
    def __init__(self, path: str, journal_trim_bytes: int = 8 << 20):
        self.path = path
        self.journal_trim_bytes = journal_trim_bytes
        self._objdir = os.path.join(path, "objects")
        self._journal_path = os.path.join(path, "journal")
        self._committed_path = os.path.join(path, "COMMITTED")
        self._journal = None
        self._seq = 0
        self.mount()

    # -- lifecycle ---------------------------------------------------------

    def mount(self) -> None:
        os.makedirs(self._objdir, exist_ok=True)
        committed = 0
        if os.path.exists(self._committed_path):
            with open(self._committed_path, "rb") as f:
                payload, _ = unframe(f.read(), 0)
            if payload is not None:
                committed = Decoder(payload).u64()
        self._seq = committed
        # replay journal records past the committed seq
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                data = f.read()
            pos = 0
            while True:
                payload, pos = unframe(data, pos)
                if payload is None:
                    break
                seq, txn = _decode_txn(payload)
                if seq > committed:
                    self._apply(txn)
                    self._seq = seq
            self._write_committed()
        self._journal = open(self._journal_path, "ab")

    def umount(self) -> None:
        if self._journal is not None:
            self._journal.flush()
            os.fsync(self._journal.fileno())
            self._journal.close()
            self._journal = None

    # -- transaction path --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        self._seq += 1
        record = frame(_encode_txn(self._seq, txn))
        self._journal.write(record)
        self._journal.flush()
        os.fsync(self._journal.fileno())
        self._apply(txn)
        self._write_committed()
        if self._journal.tell() > self.journal_trim_bytes:
            self._trim_journal()
        self._stats_invalidate()

    def _write_committed(self) -> None:
        tmp = self._committed_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame(Encoder().u64(self._seq).bytes()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._committed_path)

    def _trim_journal(self) -> None:
        self._journal.close()
        self._journal = open(self._journal_path, "wb")

    # -- apply (idempotent: safe to replay) --------------------------------

    def _data_path(self, oid: str) -> str:
        return os.path.join(self._objdir, _escape(oid) + ".data")

    def _attr_path(self, oid: str) -> str:
        return os.path.join(self._objdir, _escape(oid) + ".attr")

    def _omap_path(self, oid: str) -> str:
        return os.path.join(self._objdir, _escape(oid) + ".omap")

    def _read_omap(self, oid: str) -> Dict[str, bytes]:
        p = self._omap_path(oid)
        if not os.path.exists(p):
            return {}
        with open(p, "rb") as f:
            payload, _ = unframe(f.read(), 0)
        if payload is None:
            return {}
        return Decoder(payload).value()  # type: ignore[return-value]

    def _write_omap(self, oid: str, omap: Dict[str, bytes]) -> None:
        tmp = self._omap_path(oid) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame(Encoder().value(omap).bytes()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._omap_path(oid))

    def _read_attrs(self, oid: str) -> Dict[str, object]:
        p = self._attr_path(oid)
        if not os.path.exists(p):
            return {}
        with open(p, "rb") as f:
            payload, _ = unframe(f.read(), 0)
        if payload is None:
            return {}
        return Decoder(payload).value()  # type: ignore[return-value]

    def _write_attrs(self, oid: str, attrs: Dict[str, object]) -> None:
        tmp = self._attr_path(oid) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame(Encoder().value(attrs).bytes()))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._attr_path(oid))

    def _apply(self, txn: Transaction) -> None:
        for op in txn.ops:
            if op.op == "write":
                p = self._data_path(op.oid)
                mode = "r+b" if os.path.exists(p) else "w+b"
                with open(p, mode) as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if size < op.offset:
                        f.write(b"\0" * (op.offset - size))
                    f.seek(op.offset)
                    f.write(op.data)
                    f.flush()
                    os.fsync(f.fileno())
            elif op.op == "truncate":
                p = self._data_path(op.oid)
                if not os.path.exists(p):
                    open(p, "wb").close()
                with open(p, "r+b") as f:
                    f.truncate(op.offset)
                    f.flush()
                    os.fsync(f.fileno())
            elif op.op == "setattr":
                attrs = self._read_attrs(op.oid)
                attrs[op.attr_name] = op.attr_value
                self._write_attrs(op.oid, attrs)
                # setattr on a fresh object must create it (MemStore does)
                p = self._data_path(op.oid)
                if not os.path.exists(p):
                    open(p, "wb").close()
            elif op.op == "clone":
                import shutil

                sp = self._data_path(op.oid)
                dp = self._data_path(op.attr_name)
                if os.path.exists(dp):
                    # journal-replay idempotency: later ops in the same
                    # txn mutate the source (truncate/overwrite), so
                    # re-cloning on replay would capture post-txn bytes
                    # and destroy the snapshot.  Clone targets are
                    # create-once (unique snap seq), so an existing dst
                    # means the op already applied.
                    continue
                if not os.path.exists(sp):
                    raise FileNotFoundError(op.oid)
                shutil.copyfile(sp, dp)
                self._write_attrs(op.attr_name, self._read_attrs(op.oid))
            elif op.op == "remove":
                for p in (self._data_path(op.oid), self._attr_path(op.oid),
                          self._omap_path(op.oid)):
                    try:
                        os.remove(p)
                    except FileNotFoundError:
                        pass
            elif op.op == "omap_set":
                omap = self._read_omap(op.oid)
                omap.update(op.attr_value)
                self._write_omap(op.oid, omap)
                p = self._data_path(op.oid)
                if not os.path.exists(p):
                    open(p, "wb").close()
            elif op.op == "omap_rm":
                omap = self._read_omap(op.oid)
                for k in op.attr_value:
                    omap.pop(k, None)
                self._write_omap(op.oid, omap)
            elif op.op == "omap_clear":
                try:
                    os.remove(self._omap_path(op.oid))
                except FileNotFoundError:
                    pass
            else:
                raise ValueError(f"unknown op {op.op}")

    # -- reads (MemStore API) ----------------------------------------------

    def read(self, oid: str, offset: int = 0, length: int = -1) -> bytes:
        p = self._data_path(oid)
        if not os.path.exists(p):
            raise FileNotFoundError(oid)
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read() if length < 0 else f.read(length)

    def getattr(self, oid: str, name: str):
        if not os.path.exists(self._data_path(oid)):
            raise FileNotFoundError(oid)
        return self._read_attrs(oid).get(name)

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        if not os.path.exists(self._data_path(oid)):
            raise FileNotFoundError(oid)
        omap = self._read_omap(oid)
        if keys is None:
            return omap
        return {k: omap[k] for k in keys if k in omap}

    def stat(self, oid: str) -> int:
        p = self._data_path(oid)
        if not os.path.exists(p):
            raise FileNotFoundError(oid)
        return os.path.getsize(p)

    def exists(self, oid: str) -> bool:
        return os.path.exists(self._data_path(oid))

    def list_objects(self) -> List[str]:
        names = []
        for name in os.listdir(self._objdir):
            if name.endswith(".data"):
                names.append(_unescape(name[: -len(".data")]))
        return sorted(names)

    # test hook (scrub/EIO-path tests)
    def corrupt(self, oid: str, offset: int) -> None:
        with open(self._data_path(oid), "r+b") as f:
            f.seek(offset)
            b = f.read(1)
            f.seek(offset)
            f.write(bytes([b[0] ^ 0xFF]))
