"""Storage engines (reference: src/os -- the ObjectStore layer).

``ObjectStore.create`` (src/os/ObjectStore.cc:63) selects a backend by
name.  All backends share MemStore's API surface (queue_transaction /
read / getattr / stat / exists / list_objects), which is the subset of
ObjectStore the EC path uses (SURVEY.md L2):

* ``memstore``  -- RAM, test-grade (src/os/memstore/MemStore.cc)
* ``filestore`` -- files + crc-framed WAL journal, crash-safe
  (src/os/filestore/FileStore.cc + FileJournal)
* ``kstore``    -- everything in a KeyValueDB (src/os/kstore/KStore.cc);
  pairs with the ``lsm`` KeyValueDB for persistence
* ``blockstore`` -- raw-block data + LSM metadata + deferred-write WAL,
  the BlueStore-class production engine (src/os/bluestore/BlueStore.cc)
"""

from __future__ import annotations

from ceph_tpu.osd.memstore import MemStore
from ceph_tpu.objectstore.blockstore import BlockStore
from ceph_tpu.objectstore.filestore import FileStore
from ceph_tpu.objectstore.kstore import KStore


def create(kind: str, path: str = "", **kw):
    """``kind`` may carry a compression suffix for blockstore
    ("blockstore:zlib" -- the bluestore_compression_algorithm role)."""
    kind, _, alg = kind.partition(":")
    if kind == "memstore":
        return MemStore()
    if kind == "filestore":
        if not path:
            raise ValueError("filestore needs a data path")
        return FileStore(path)
    if kind == "kstore":
        if not path:
            raise ValueError("kstore needs a data path")
        return KStore(path)
    if kind == "blockstore":
        if not path:
            raise ValueError("blockstore needs a data path")
        kw_alg = kw.pop("compression", None)  # pop BEFORE the or-else:
        # a short-circuit would leave a duplicate kwarg in **kw
        return BlockStore(path, compression=alg or kw_alg, **kw)
    raise ValueError(f"unknown objectstore backend {kind!r}")


__all__ = ["create", "MemStore", "FileStore", "KStore", "BlockStore"]
