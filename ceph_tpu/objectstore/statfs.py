"""Shared ``stats()`` mixin for the persistent ObjectStore backends.

MemStore maintains its totals exactly and incrementally at the
transaction swap (O(1) per ``stats()``); the persistent backends'
on-disk layouts make per-op delta accounting invasive, so they memoize
ONE usage scan and invalidate it per queued transaction.  A quiet store
answers every mgr report from the cache; a store under write load pays
one scan per report interval at most -- bounded, and only for the
persistent-backend deployments (the default memstore path never scans).
"""

from __future__ import annotations

from typing import Dict


class ScanStatsMixin:
    """``stats()`` = memoized usage scan; subclasses call
    ``_stats_invalidate()`` from ``queue_transaction``."""

    _stats_cache = None

    def _stats_invalidate(self) -> None:
        self._stats_cache = None

    def stats(self) -> Dict[str, int]:
        cached = self._stats_cache
        if cached is not None:
            return dict(cached)
        shards = metas = nbytes = 0
        for oid in self.list_objects():
            try:
                size = self.stat(oid)
            except FileNotFoundError:
                continue  # raced a concurrent transaction
            nbytes += size
            if oid.endswith("@meta"):
                metas += 1
            else:
                shards += 1
        cached = {
            "objects": shards + metas,
            "shards": shards,
            "metas": metas,
            "bytes": nbytes,
        }
        self._stats_cache = cached
        return dict(cached)
