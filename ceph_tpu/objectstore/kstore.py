"""KV-backed ObjectStore (KStore equivalent).

Reference: src/os/kstore/KStore.cc (3358 LoC) -- object data and metadata
both live in the KeyValueDB: data is chunked into fixed-size stripes under
a per-object key prefix, metadata (size, xattrs) under another.  Pairs
with the ``lsm`` KeyValueDB for a fully persistent store, or ``memdb``
for a RAM one.

Key layout (prefix, key):
  ("M", oid)            -> framed {size, xattrs} metadata
  ("D", f"{oid}.{n:08d}") -> data stripe n (stripe_size bytes, tail short)
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ceph_tpu import kv as kv_mod
from ceph_tpu.kv.keyvaluedb import KeyValueDB, KVTransaction
from ceph_tpu.objectstore.statfs import ScanStatsMixin
from ceph_tpu.osd.types import Transaction
from ceph_tpu.utils.encoding import Decoder, Encoder


class KStore(ScanStatsMixin):
    def __init__(self, path: str, db: Optional[KeyValueDB] = None,
                 stripe_size: int = 64 * 1024):
        self.stripe_size = stripe_size
        self.db = db if db is not None else kv_mod.create("lsm", path)
        self.db.open()

    def umount(self) -> None:
        self.db.close()

    # -- metadata helpers --------------------------------------------------

    def _get_meta(self, oid: str) -> Optional[dict]:
        raw = self.db.get("M", oid)
        if raw is None:
            return None
        return Decoder(raw).value()  # type: ignore[return-value]

    @staticmethod
    def _meta_bytes(meta: dict) -> bytes:
        return Encoder().value(meta).bytes()

    def _stripe_key(self, oid: str, n: int) -> str:
        return f"{oid}.{n:08d}"

    def _omap_key(self, oid: str, key: str) -> str:
        return f"{oid}\x00{key}"

    def _omap_db_keys(self, oid: str) -> List[str]:
        prefix = oid + "\x00"
        return [
            k[len(prefix):]
            for k, _ in self.db.get_iterator("O")
            if k.startswith(prefix)
        ]

    # -- transaction path --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        """Stage everything, then one atomic KV batch (the reference's
        one-rocksdb-WriteBatch-per-transaction contract)."""
        batch = KVTransaction()
        metas: Dict[str, Optional[dict]] = {}
        stripes: Dict[str, Dict[int, bytearray]] = {}
        #: staged omap mutations per oid: key -> bytes (set) | None (rm)
        omaps: Dict[str, Dict[str, Optional[bytes]]] = {}
        removed: set = set()  # oids removed earlier in this txn

        def meta_for(oid: str) -> dict:
            if oid not in metas:
                metas[oid] = self._get_meta(oid) or {"size": 0, "xattrs": {}}
            m = metas[oid]
            if m is None:  # removed earlier in this txn, then recreated
                m = {"size": 0, "xattrs": {}}
                metas[oid] = m
            return m

        def stripe_for(oid: str, n: int) -> bytearray:
            obj = stripes.setdefault(oid, {})
            if n not in obj:
                raw = (
                    None if oid in removed
                    else self.db.get("D", self._stripe_key(oid, n))
                )
                obj[n] = bytearray(raw) if raw is not None else bytearray()
            return obj[n]

        for op in txn.ops:
            if op.op == "write":
                meta = meta_for(op.oid)
                end = op.offset + len(op.data)
                pos = op.offset
                dpos = 0
                while pos < end:
                    n, off = divmod(pos, self.stripe_size)
                    take = min(self.stripe_size - off, end - pos)
                    st = stripe_for(op.oid, n)
                    if len(st) < off + take:
                        st.extend(b"\0" * (off + take - len(st)))
                    st[off : off + take] = op.data[dpos : dpos + take]
                    pos += take
                    dpos += take
                meta["size"] = max(meta["size"], end)
            elif op.op == "truncate":
                meta = meta_for(op.oid)
                old_size = meta["size"]
                meta["size"] = op.offset
                if op.offset < old_size:
                    first_dead = (
                        op.offset + self.stripe_size - 1
                    ) // self.stripe_size
                    for n in range(first_dead,
                                   (old_size // self.stripe_size) + 1):
                        stripes.setdefault(op.oid, {})[n] = bytearray()
                    ln, loff = divmod(op.offset, self.stripe_size)
                    if loff:
                        st = stripe_for(op.oid, ln)
                        del st[loff:]
            elif op.op == "setattr":
                meta_for(op.oid)["xattrs"][op.attr_name] = op.attr_value
            elif op.op == "clone":
                src_meta = metas[op.oid] if op.oid in metas \
                    else self._get_meta(op.oid)
                if src_meta is None:
                    raise FileNotFoundError(op.oid)
                dst = op.attr_name
                metas[dst] = {"size": src_meta["size"],
                              "xattrs": dict(src_meta["xattrs"])}
                removed.discard(dst)
                for n in range(src_meta["size"] // self.stripe_size + 1):
                    st = stripes.get(op.oid, {}).get(n)
                    if st is None:
                        raw = self.db.get("D", self._stripe_key(op.oid, n))
                        st = bytearray(raw) if raw is not None else None
                    if st is not None:
                        stripes.setdefault(dst, {})[n] = bytearray(st)
            elif op.op == "remove":
                # dead-stripe range must cover the ON-DISK size too: a
                # shrink staged earlier in this txn would otherwise leave
                # orphan stripes beyond the staged size, and their stale
                # bytes could resurface in a later sparse write
                staged = metas.get(op.oid)
                disk = self._get_meta(op.oid)
                max_size = max(
                    (m["size"] for m in (staged, disk) if m), default=0
                )
                metas[op.oid] = None
                stripes.pop(op.oid, None)
                omaps.pop(op.oid, None)
                removed.add(op.oid)
                batch.rmkey("M", op.oid)
                for n in range(max_size // self.stripe_size + 1):
                    batch.rmkey("D", self._stripe_key(op.oid, n))
                for k in self._omap_db_keys(op.oid):
                    batch.rmkey("O", self._omap_key(op.oid, k))
            elif op.op == "omap_set":
                meta_for(op.oid)
                omaps.setdefault(op.oid, {}).update(op.attr_value)
            elif op.op == "omap_rm":
                staged_omap = omaps.setdefault(op.oid, {})
                for k in op.attr_value:
                    staged_omap[k] = None
            elif op.op == "omap_clear":
                staged_omap = omaps.setdefault(op.oid, {})
                staged_omap.clear()
                keys = (
                    [] if op.oid in removed else self._omap_db_keys(op.oid)
                )
                for k in keys:
                    staged_omap[k] = None
            else:
                raise ValueError(f"unknown op {op.op}")

        for oid, meta in metas.items():
            if meta is None:
                continue
            batch.set("M", oid, self._meta_bytes(meta))
        for oid, obj in stripes.items():
            if metas.get(oid, True) is None:
                continue
            for n, st in obj.items():
                if st:
                    batch.set("D", self._stripe_key(oid, n), bytes(st))
                else:
                    batch.rmkey("D", self._stripe_key(oid, n))
        for oid, staged_omap in omaps.items():
            if metas.get(oid, True) is None:
                continue
            for k, v in staged_omap.items():
                if v is None:
                    batch.rmkey("O", self._omap_key(oid, k))
                else:
                    batch.set("O", self._omap_key(oid, k), v)
        self.db.submit_transaction(batch, sync=True)
        self._stats_invalidate()

    # -- reads (MemStore API) ----------------------------------------------

    def read(self, oid: str, offset: int = 0, length: int = -1) -> bytes:
        meta = self._get_meta(oid)
        if meta is None:
            raise FileNotFoundError(oid)
        size = meta["size"]
        end = size if length < 0 else min(size, offset + length)
        if offset >= end:
            return b""
        out = bytearray(end - offset)
        pos = offset
        while pos < end:
            n, off = divmod(pos, self.stripe_size)
            take = min(self.stripe_size - off, end - pos)
            raw = self.db.get("D", self._stripe_key(oid, n)) or b""
            chunk = raw[off : off + take]
            out[pos - offset : pos - offset + len(chunk)] = chunk
            pos += take
        return bytes(out)

    def getattr(self, oid: str, name: str):
        meta = self._get_meta(oid)
        if meta is None:
            raise FileNotFoundError(oid)
        return meta["xattrs"].get(name)

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        if self._get_meta(oid) is None:
            raise FileNotFoundError(oid)
        if keys is not None:
            out = {}
            for k in keys:
                v = self.db.get("O", self._omap_key(oid, k))
                if v is not None:
                    out[k] = v
            return out
        return {
            k: self.db.get("O", self._omap_key(oid, k))
            for k in self._omap_db_keys(oid)
        }

    def stat(self, oid: str) -> int:
        meta = self._get_meta(oid)
        if meta is None:
            raise FileNotFoundError(oid)
        return meta["size"]

    def exists(self, oid: str) -> bool:
        return self._get_meta(oid) is not None

    def list_objects(self) -> List[str]:
        return sorted(k for k, _ in self.db.get_iterator("M"))

    # test hook (scrub/EIO-path tests)
    def corrupt(self, oid: str, offset: int) -> None:
        n, off = divmod(offset, self.stripe_size)
        key = self._stripe_key(oid, n)
        raw = bytearray(self.db.get("D", key))
        raw[off] ^= 0xFF
        batch = KVTransaction().set("D", key, bytes(raw))
        self.db.submit_transaction(batch, sync=True)
