"""ISA-L-compatible matrix constructions (GF(2^8), poly 0x11D).

Mirrors the semantics of isa-l's gf_gen_rs_matrix / gf_gen_cauchy1_matrix as
used by the reference isa plugin (reference: src/erasure-code/isa/
ErasureCodeIsa.cc:383-386): full (k+m) x k systematic matrices with an
identity top block.  GF(2^8) with polynomial 0x11D is shared with jerasure's
w=8 field, so element values interoperate.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf


def gen_rs_matrix(k: int, m: int) -> np.ndarray:
    """(k+m) x k: identity on top; coding row r = [1, g, g^2, ...], g = 2^r.

    Matches isa-l gf_gen_rs_matrix(a, k+m, k).  Only guaranteed invertible for
    the parameter ranges the reference plugin enforces (k<=32, m<=4, and
    m==4 -> k<=21; reference: src/erasure-code/isa/ErasureCodeIsa.cc:322-363).
    """
    F = gf(8)
    A = np.zeros((k + m, k), dtype=np.uint32)
    for i in range(k):
        A[i, i] = 1
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            A[k + r, j] = p
            p = F.mul(p, gen)
        gen = F.mul(gen, 2)
    return A


def gen_cauchy1_matrix(k: int, m: int) -> np.ndarray:
    """(k+m) x k: identity on top; coding element [k+r, j] = inv((k+r) ^ j).

    Matches isa-l gf_gen_cauchy1_matrix.
    """
    F = gf(8)
    A = np.zeros((k + m, k), dtype=np.uint32)
    for i in range(k):
        A[i, i] = 1
    for r in range(m):
        for j in range(k):
            A[k + r, j] = F.inv((k + r) ^ j)
    return A
