"""Product-matrix MSR regenerating code construction (d = 2k-2).

Implements the minimum-storage-regenerating (MSR) point of the
product-matrix framework of Rashmi, Shah & Kumar ("Optimal
Exact-Regenerating Codes for Distributed Storage at the MSR and MBR
Points via a Product-Matrix Construction"; PAPERS "Fast Product-Matrix
Regenerating Codes" is the systems treatment this module follows):

* every node stores alpha = k-1 sub-chunks; the B = k*(k-1) message
  symbols fill two symmetric alpha x alpha matrices S1, S2 and node i
  stores ``psi_i^T @ [S1; S2]`` where ``psi_i = [phi_i | lam_i^alpha
  phi_i]`` and ``phi_i = (1, lam_i, ..., lam_i^(alpha-1))``;
* a lost node f is regenerated from ANY d = 2k-2 survivors, each
  contributing ONE sub-chunk worth (beta = chunk/alpha bytes): the dot
  of its alpha stored sub-chunks with ``phi_f`` -- so repair moves
  d*beta = 2*chunk bytes instead of k*chunk (ratio 2/k);
* because B = k*alpha exactly, the code LINEARIZES: stacking the k data
  nodes' sub-chunks gives an invertible kα x kα map from the free
  symbols, so the whole code collapses to ONE systematic GF(2^8)
  generator ``G`` over *virtual rows* (node i's sub-chunk j = virtual
  row i*alpha+j).  Encode/decode/repair are then all plain GF matmuls
  -- exactly the shape `ops/pipeline.py` batches on device.

Everything here is host-side construction (numpy over ``ops/gf.py``);
the device dispatch lives in ``plugins/regen.py``.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ceph_tpu.ops.gf import gf


def _select_points(field, n: int, alpha: int) -> List[int]:
    """n evaluation points whose alpha-th powers are pairwise distinct
    (the product-matrix MSR admissibility condition: Lambda's diagonal
    entries must differ; distinct lam^alpha implies distinct lam).  In
    GF(2^8)* the alpha-th powers form a subgroup of index gcd(255,
    alpha), so 255/gcd(255, alpha) nonzero points exist, plus zero."""
    points: List[int] = []
    seen_pow = set()
    for x in range(field.order):
        p = field_pow(field, x, alpha)
        if p in seen_pow:
            continue
        seen_pow.add(p)
        points.append(x)
        if len(points) == n:
            return points
    raise ValueError(
        f"only {len(points)} evaluation points with distinct "
        f"alpha-th powers exist in GF(2^{field.w}) for alpha={alpha}; "
        f"need n={n}"
    )


def field_pow(field, x: int, e: int) -> int:
    """x**e in the field (log/exp when available, square-multiply else)."""
    if e == 0:
        return 1
    if x == 0:
        return 0
    r = 1
    base = x
    while e:
        if e & 1:
            r = field.mul(r, base)
        base = field.mul(base, base)
        e >>= 1
    return r


class ProductMatrixMSR:
    """The construction for one (k, m) pool: n = k+m nodes, d = 2k-2.

    Exposes the three matrices the codec and the repair lane need:

    * :attr:`generator` -- (m*alpha, k*alpha) systematic generator over
      virtual rows (parity virtual rows from data virtual rows);
    * :meth:`repair_coeffs` -- phi_f, the alpha GF coefficients EVERY
      helper applies to its own sub-chunks (depends only on the lost
      node, so one wire-carried vector covers the whole helper set);
    * :meth:`repair_matrix` -- R_f, the (alpha, d) matrix regenerating
      the lost node's content from the d stacked helper symbols
      (depends on the helper set; cached by the caller per signature).
    """

    def __init__(self, k: int, m: int, w: int = 8):
        if w != 8:
            raise ValueError(f"product-matrix MSR supports w=8, not w={w}")
        if k < 2:
            raise ValueError(f"k={k} must be >= 2")
        if m < k - 1:
            raise ValueError(
                f"m={m} must be >= k-1={k - 1} so d=2k-2 helpers exist "
                f"among the n-1 survivors"
            )
        self.k, self.m, self.w = k, m, w
        self.n = k + m
        self.alpha = k - 1
        self.d = 2 * k - 2
        self.B = k * self.alpha
        self._field = gf(w)
        self._lam = _select_points(self._field, self.n, self.alpha)
        self._lam_alpha = [
            field_pow(self._field, x, self.alpha) for x in self._lam
        ]
        #: phi_i = (1, lam_i, ..., lam_i^(alpha-1)) per node, (n, alpha)
        self._phi = np.array(
            [[field_pow(self._field, x, j) for j in range(self.alpha)]
             for x in self._lam],
            dtype=np.uint32,
        )
        self.generator = self._build_generator()
        self._repair_cache: Dict[Tuple[int, Tuple[int, ...]], np.ndarray] = {}

    # -- construction ------------------------------------------------------

    def _free_symbol_index(self) -> Dict[Tuple[int, int, int], int]:
        """Map (matrix 0/1, row, col) of S1/S2 to its free-symbol slot
        (upper triangle incl. diagonal; symmetry folds the rest)."""
        idx: Dict[Tuple[int, int, int], int] = {}
        slot = 0
        for which in (0, 1):
            for i in range(self.alpha):
                for j in range(i, self.alpha):
                    idx[(which, i, j)] = slot
                    idx[(which, j, i)] = slot
                    slot += 1
        assert slot == self.B
        return idx

    def _node_rows(self, node: int, idx) -> np.ndarray:
        """A_i: node ``node``'s alpha stored sub-chunks as linear forms
        over the B free symbols -- sub-chunk j = sum_t phi[t]*S1[t,j] +
        lam^alpha * sum_t phi[t]*S2[t,j]."""
        field = self._field
        rows = np.zeros((self.alpha, self.B), dtype=np.uint32)
        la = self._lam_alpha[node]
        for j in range(self.alpha):
            for t in range(self.alpha):
                c = int(self._phi[node, t])
                rows[j, idx[(0, t, j)]] ^= c
                rows[j, idx[(1, t, j)]] ^= field.mul(la, c)
        return rows

    def _build_generator(self) -> np.ndarray:
        field = self._field
        idx = self._free_symbol_index()
        blocks = [self._node_rows(i, idx) for i in range(self.n)]
        a_data = np.vstack(blocks[: self.k])  # (k*alpha, B), B == k*alpha
        a_parity = np.vstack(blocks[self.k:])  # (m*alpha, B)
        try:
            inv = field.mat_invert(a_data)
        except np.linalg.LinAlgError as e:  # pragma: no cover
            raise ValueError(
                f"product-matrix data block singular for k={self.k} "
                f"m={self.m} (bad evaluation points)"
            ) from e
        return field.mat_mul(a_parity, inv).astype(np.uint32)

    # -- repair algebra ----------------------------------------------------

    def repair_coeffs(self, lost: int) -> List[int]:
        """phi_f: the coefficients every helper dots its own alpha
        sub-chunks with (identical across helpers -- only the LOST node
        determines them, which is what lets one wire field serve the
        whole corked read burst)."""
        if not 0 <= lost < self.n:
            raise ValueError(f"lost={lost} out of range for n={self.n}")
        return [int(c) for c in self._phi[lost]]

    def repair_matrix(self, lost: int,
                      helpers: Sequence[int]) -> np.ndarray:
        """R_f: (alpha, d) over GF(2^8); lost content = R_f @ stacked
        helper symbols (helpers in the given order).  Derivation: the d
        helpers stack to ``Psi_D @ (M phi_f)`` with Psi_D invertible
        (Vandermonde, distinct lam), and by S1/S2 symmetry the lost
        row is ``[I | lam_f^alpha I] @ (M phi_f)``."""
        helpers = tuple(int(h) for h in helpers)
        if len(helpers) != self.d:
            raise ValueError(
                f"regeneration needs exactly d={self.d} helpers, "
                f"got {len(helpers)}"
            )
        if lost in helpers:
            raise ValueError(f"lost node {lost} cannot be its own helper")
        if len(set(helpers)) != self.d:
            raise ValueError(f"duplicate helpers: {helpers}")
        for h in helpers:
            if not 0 <= h < self.n:
                raise ValueError(f"helper {h} out of range for n={self.n}")
        key = (int(lost), helpers)
        cached = self._repair_cache.get(key)
        if cached is not None:
            return cached
        field = self._field
        psi = np.zeros((self.d, self.d), dtype=np.uint32)
        for r, h in enumerate(helpers):
            psi[r, : self.alpha] = self._phi[h]
            la = self._lam_alpha[h]
            for j in range(self.alpha):
                psi[r, self.alpha + j] = field.mul(la, int(self._phi[h, j]))
        psi_inv = field.mat_invert(psi)
        sel = np.zeros((self.alpha, self.d), dtype=np.uint32)
        la_f = self._lam_alpha[lost]
        for j in range(self.alpha):
            sel[j, j] = 1
            sel[j, self.alpha + j] = la_f
        rf = field.mat_mul(sel, psi_inv).astype(np.uint32)
        self._repair_cache[key] = rf
        return rf
