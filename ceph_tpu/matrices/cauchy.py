"""Cauchy coding-matrix construction (jerasure `cauchy` family).

Rebuilt from the published algorithms (Plank & Xu, "Optimizing Cauchy
Reed-Solomon Codes for Fault-Tolerant Network Storage Applications", NCA-06,
which is what jerasure's cauchy.c implements).  Reference call sites:
src/erasure-code/jerasure/ErasureCodeJerasure.cc:315-330
(`cauchy_original_coding_matrix`, `cauchy_good_general_coding_matrix`).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf
from ceph_tpu.matrices.bitmatrix import n_ones


def original_coding_matrix(k: int, m: int, w: int) -> np.ndarray:
    """M[i][j] = 1 / (i XOR (m+j)) over GF(2^w)."""
    if w < 30 and (k + m) > (1 << w):
        raise ValueError("k+m exceeds field size")
    F = gf(w)
    M = np.zeros((m, k), dtype=np.uint32)
    for i in range(m):
        for j in range(k):
            M[i, j] = F.inv(i ^ (m + j))
    return M


def improve_coding_matrix(k: int, m: int, w: int, M: np.ndarray) -> np.ndarray:
    """jerasure's cauchy_improve_coding_matrix:

    1. divide each column by its first-row element (first row becomes ones);
    2. for every other row, find the element whose inverse, multiplied through
       the row, minimizes the total bitmatrix one-count; apply the best.
    """
    F = gf(w)
    M = M.astype(np.uint32).copy()
    for j in range(k):
        c = int(M[0, j])
        if c != 1:
            cinv = F.inv(c)
            for i in range(m):
                M[i, j] = F.mul(int(M[i, j]), cinv)
    for i in range(1, m):
        best_ones = sum(n_ones(int(M[i, j]), w) for j in range(k))
        best_factor = 1
        for j in range(k):
            e = int(M[i, j])
            if e != 1:
                f = F.inv(e)
                tot = sum(n_ones(F.mul(int(M[i, x]), f), w) for x in range(k))
                if tot < best_ones:
                    best_ones = tot
                    best_factor = f
        if best_factor != 1:
            for j in range(k):
                M[i, j] = F.mul(int(M[i, j]), best_factor)
    return M


def good_general_coding_matrix(k: int, m: int, w: int) -> np.ndarray:
    """cauchy_good: original matrix run through the one-count improvement.

    For m == 2 and small w jerasure special-cases to a precomputed optimal
    matrix (cauchy_best_r6); we apply the general improvement uniformly,
    which matches cauchy_good_general_coding_matrix semantics.
    """
    M = original_coding_matrix(k, m, w)
    return improve_coding_matrix(k, m, w, M)
