"""RAID-6 bitmatrix code constructions: liberation, blaum_roth, liber8tion.

Reference call sites: src/erasure-code/jerasure/ErasureCodeJerasure.cc:444-448
(liberation), :468-472 (blaum_roth), :499-503 (liber8tion).  All are m=2
bitmatrix codes driven through the packetized GF(2) engine.

Provenance notes (the jerasure C source is an empty submodule in the
reference checkout):

* liberation -- rebuilt from Plank, "The RAID-6 Liberation Codes" (FAST'08):
  P block = k identity matrices; Q block j = cyclically shifted identity
  (row i has a one at column (i+j) mod w) plus, for j>0, one extra bit at
  row (j*(w-1)//2) mod w, column (row+j-1) mod w.
* blaum_roth -- rebuilt from the Blaum-Roth construction over the ring
  R_p = GF(2)[x]/M_p(x), p = w+1 prime: Q block j represents multiply-by-x^j;
  column c is unit vector e_((j+c) mod p) when the exponent is < w and the
  all-ones column when it equals w.
* liber8tion -- the published matrices are explicit search results (Plank,
  "Uber-CSHR and Liber8tion codes", 2008) not reconstructible from an
  algorithm; we substitute an equivalent-capability m=2, w=8 code (the
  bitmatrix expansion of the RAID6 Reed-Solomon matrix).  Same API, same
  fault tolerance, NOT bit-identical to jerasure's liber8tion output.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.matrices.bitmatrix import matrix_to_bitmatrix
from ceph_tpu.matrices.reed_sol import r6_coding_matrix


def _identity_row_block(k: int, w: int) -> np.ndarray:
    B = np.zeros((w, k * w), dtype=np.uint8)
    for j in range(k):
        B[:, j * w : (j + 1) * w] = np.eye(w, dtype=np.uint8)
    return B


def liberation_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w) x (kw) liberation bitmatrix; w prime > 2, k <= w."""
    if k > w:
        raise ValueError("k must be <= w")
    B = np.zeros((2 * w, k * w), dtype=np.uint8)
    B[:w] = _identity_row_block(k, w)
    for j in range(k):
        for i in range(w):
            B[w + i, j * w + (i + j) % w] = 1
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            B[w + i, j * w + (i + j - 1) % w] = 1
    return B


def blaum_roth_coding_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w) x (kw) Blaum-Roth bitmatrix; w+1 prime, k <= w."""
    if k > w:
        raise ValueError("k must be <= w")
    p = w + 1
    B = np.zeros((2 * w, k * w), dtype=np.uint8)
    B[:w] = _identity_row_block(k, w)
    for j in range(k):
        for c in range(w):
            e = (j + c) % p
            if e == w:
                B[w:, j * w + c] = 1  # x^w = 1 + x + ... + x^(w-1) in R_p
            else:
                B[w + e, j * w + c] = 1
    return B


def liber8tion_coding_bitmatrix(k: int) -> np.ndarray:
    """m=2, w=8, k<=8 bitmatrix (capability-equivalent substitute, see above)."""
    if k > 8:
        raise ValueError("k must be <= 8")
    return matrix_to_bitmatrix(r6_coding_matrix(k, 8), 8)
