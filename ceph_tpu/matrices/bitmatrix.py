"""GF(2) bit-matrix utilities (jerasure bitmatrix convention).

A w-bit field element e expands to a w x w 0/1 matrix over GF(2): column x is
the bit-decomposition of e * 2^x (bit l of that product sits at row l).  A
k x m element matrix expands to an (m*w) x (k*w) bitmatrix; bitmatrix codes
(cauchy, liberation family) encode by XORing data *packets* selected by the
rows (reference: jerasure_matrix_to_bitmatrix /jerasure_schedule_encode call
sites at src/erasure-code/jerasure/ErasureCodeJerasure.cc:298-302,259-261).

This bit-level view is also exactly what the TPU engine executes: a GF(2)
matmul on the MXU (see ceph_tpu/ops/xla_gf.py).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf


def element_bitmatrix(e: int, w: int) -> np.ndarray:
    """w x w bitmatrix of multiply-by-e: out[l, x] = bit l of (e * 2^x)."""
    F = gf(w)
    B = np.zeros((w, w), dtype=np.uint8)
    v = e
    for x in range(w):
        for l in range(w):
            B[l, x] = (v >> l) & 1
        v = F.mul(v, 2)
    return B


def matrix_to_bitmatrix(M: np.ndarray, w: int) -> np.ndarray:
    """Expand an m x k element matrix into an (m*w) x (k*w) GF(2) bitmatrix."""
    # runtime backstop for the cephlint jax-gf-dtype-drift rule: a float
    # element matrix (e.g. np.zeros without dtype) would int()-truncate
    # per element below and build a plausible-but-wrong bitmatrix
    assert np.issubdtype(np.asarray(M).dtype, np.integer), \
        f"element matrix must be an integer dtype, got {np.asarray(M).dtype}"
    m, k = M.shape
    B = np.zeros((m * w, k * w), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            B[i * w : (i + 1) * w, j * w : (j + 1) * w] = element_bitmatrix(
                int(M[i, j]), w
            )
    return B


def n_ones(e: int, w: int) -> int:
    """Number of ones in the bitmatrix of e (jerasure cauchy_n_ones)."""
    F = gf(w)
    total = 0
    v = e
    for _ in range(w):
        total += bin(v).count("1")
        v = F.mul(v, 2)
    return total


def invert_bitmatrix(B: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) (Gauss-Jordan with XOR rows)."""
    # dtype backstop (cephlint jax-gf-dtype-drift): float input would
    # silently truncate through the astype below
    assert np.issubdtype(np.asarray(B).dtype, np.integer), \
        f"bitmatrix must be an integer dtype, got {np.asarray(B).dtype}"
    B = B.astype(np.uint8).copy()
    n = B.shape[0]
    assert B.shape == (n, n)
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = -1
        for r in range(col, n):
            if B[r, col]:
                pivot = r
                break
        if pivot < 0:
            raise np.linalg.LinAlgError("singular bitmatrix")
        if pivot != col:
            B[[col, pivot]] = B[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        for r in range(n):
            if r != col and B[r, col]:
                B[r, :] ^= B[col, :]
                inv[r, :] ^= inv[col, :]
    return inv


def survivor_decode_bitmatrix(bitmatrix: np.ndarray, k: int, w: int,
                              sel, erased_data) -> np.ndarray:
    """Decode rows for erased DATA chunks: assemble the survivor
    equation system (identity rows for surviving data chunks, coding
    bitmatrix rows for surviving parities), invert it, and return the
    rows that reconstruct each erased chunk -- the one GF(2) recipe the
    CPU oracle, the XLA engine and the benchmark all share.

    ``sel``: k surviving chunk ids (data ids < k, parity ids >= k);
    ``erased_data``: erased data-chunk ids; returns a
    [len(erased_data)*w, k*w] bitmatrix applied to the survivors in
    ``sel`` order."""
    assert bitmatrix.dtype == np.uint8, \
        f"coding bitmatrix must be uint8, got {bitmatrix.dtype}"
    A = np.zeros((k * w, k * w), dtype=np.uint8)
    for r, cid in enumerate(sel):
        if cid < k:
            A[r * w:(r + 1) * w, cid * w:(cid + 1) * w] = np.eye(
                w, dtype=np.uint8)
        else:
            A[r * w:(r + 1) * w, :] = bitmatrix[
                (cid - k) * w:(cid - k + 1) * w, :]
    inv = invert_bitmatrix(A)
    return np.concatenate(
        [inv[e * w:(e + 1) * w, :] for e in erased_data])
