"""Reed-Solomon coding-matrix construction (jerasure `reed_sol` family).

Reimplements, from the published algorithm, the matrix constructions used by
the reference's jerasure plugin (reference: src/erasure-code/jerasure/
ErasureCodeJerasure.cc:196-199 `reed_sol_vandermonde_coding_matrix`, :247-250
`reed_sol_r6_coding_matrix`).  The construction follows Plank & Ding,
"Note: Correction to the 1997 Tutorial on Reed-Solomon Coding" (2003), which
is the algorithm jerasure 2.0 implements:

1. build the (k+m) x k Vandermonde matrix V[i][j] = i^j over GF(2^w)
   (row 0 = [1,0,0,...], row 1 all ones, row i = powers of i);
2. elementary *column* operations to turn the top k x k square into the
   identity (column ops preserve the any-k-rows-invertible property);
3. scale so the first parity row (row k) is all ones -- the invariant the
   reference decode path relies on (jerasure_matrix_decode is called with
   row_k_ones=1, reference: src/erasure-code/jerasure/ErasureCodeJerasure.cc:163).

The bottom m rows are the coding matrix.

NOTE on provenance: the jerasure C source is an empty git-submodule directory
in the reference checkout, so this construction was rebuilt from the published
papers, not transcribed.  Invariants enforced by tests: systematic top block,
row k all ones, MDS under exhaustive erasure enumeration.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf


def big_vandermonde_distribution_matrix(rows: int, cols: int, w: int) -> np.ndarray:
    """(rows x cols) systematic distribution matrix, top cols x cols identity."""
    if rows < cols:
        raise ValueError("rows must be >= cols")
    if rows > (1 << w):
        raise ValueError(f"rows={rows} exceeds field size 2^{w}")
    F = gf(w)
    V = np.zeros((rows, cols), dtype=np.uint32)
    for i in range(rows):
        V[i, 0] = 1
        for j in range(1, cols):
            V[i, j] = F.mul(int(V[i, j - 1]), i)

    # Elementary column operations: make the top square the identity.
    for i in range(cols):
        if V[i, i] == 0:
            for j in range(i + 1, cols):
                if V[i, j] != 0:
                    V[:, [i, j]] = V[:, [j, i]]
                    break
            else:
                raise ValueError("Vandermonde elimination failed (singular)")
        p = int(V[i, i])
        if p != 1:
            pinv = F.inv(p)
            for r in range(rows):
                V[r, i] = F.mul(pinv, int(V[r, i]))
        for j in range(cols):
            f = int(V[i, j])
            if j != i and f != 0:
                for r in range(rows):
                    V[r, j] ^= F.mul(f, int(V[r, i]))

    # Make row `cols` (the first parity row) all ones: scale parity part of
    # each column by the inverse of its row-cols element.  (Equivalent to a
    # column scaling followed by a row scaling of the identity block.)
    if rows > cols:
        for j in range(cols):
            c = int(V[cols, j])
            if c == 0:
                raise ValueError("parity row has a zero entry; cannot normalize")
            if c != 1:
                cinv = F.inv(c)
                for r in range(cols, rows):
                    V[r, j] = F.mul(cinv, int(V[r, j]))
    return V


def vandermonde_coding_matrix(k: int, m: int, w: int) -> np.ndarray:
    """m x k coding matrix: bottom m rows of the distribution matrix."""
    V = big_vandermonde_distribution_matrix(k + m, k, w)
    return np.ascontiguousarray(V[k:, :])


def r6_coding_matrix(k: int, w: int) -> np.ndarray:
    """RAID6-optimized matrix: row0 all ones, row1 = [1, 2, 4, ...] = 2^j.

    Reference behavior: ErasureCodeJerasureReedSolomonRAID6 forces m=2
    (src/erasure-code/jerasure/ErasureCodeJerasure.cc:234-236) and encodes
    with reed_sol_r6_encode, whose parities are P = XOR(d_j) and
    Q = XOR(2^j * d_j).
    """
    if w not in (8, 16, 32):
        raise ValueError("w must be 8, 16 or 32")
    F = gf(w)
    M = np.zeros((2, k), dtype=np.uint32)
    M[0, :] = 1
    t = 1
    M[1, 0] = 1
    for j in range(1, k):
        t = F.mul(t, 2)
        M[1, j] = t
    return M
