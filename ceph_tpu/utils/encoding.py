"""Binary encode/decode framework (denc-lite).

Reference: src/include/encoding.h (1364 LoC) / src/include/denc.h -- every
persistent or wire struct in the reference serializes through one small
framework with explicit little-endian integer widths, length-prefixed
blobs, and crc-guarded envelopes.  This is the same idea reduced to what
the TPU framework persists: journal records, KV log records and object
metadata.

Value model (self-describing, tagged):
  None, bool, int (u64/zigzag-s64), bytes, str, list, tuple,
  dict[str, value].  Lists and tuples round-trip as distinct types.

Framed records (``frame``/``unframe``) carry ``MAGIC | len | crc32c |
payload`` so torn tail writes after a crash are detected and discarded --
the role of the reference's per-entry crcs in the FileStore journal
(src/os/filestore/FileJournal.cc) and the message envelope crcs
(src/msg/Message.cc).

Zero-copy output mode (round 8): an ``Encoder`` holds a PART LIST, not a
growing buffer.  ``bytes`` objects handed to :meth:`Encoder.blob` are
referenced (immutable -- no copy is ever needed); mutable buffers are
defensively copied unless the caller uses :meth:`Encoder.blob_ref`,
which references a ``memoryview`` under the contract that the caller
MUST NOT mutate the buffer until the encoded record has been fully
written out (the bufferlist discipline of src/include/buffer.h -- the
reference also shares raw pointers along the write path and relies on
the same contract).  ``parts()``/``frame_parts()`` emit a header + part
list suitable for ``writer.writelines`` scatter-gather sends, and
``crc32c_parts`` folds the frame crc over the parts incrementally
(crc32c chains: ``crc(a||b) == crc(b, crc(a))``), so a large payload
crosses the messenger with zero intermediate concatenations.
"""

from __future__ import annotations

import struct
from typing import Any, List, Optional, Tuple

import numpy as np

from ceph_tpu.native.gf_native import crc32c
from ceph_tpu.profiling import ledger as _profiler

#: wire-tax cost center for the incremental frame digest (one marker,
#: fetched once; a global-bool branch when profiling is off)
_PS_CRC = _profiler.stage("wire.crc32c")

_MAGIC = 0xCE9B10C5

# value tags
_T_NONE, _T_FALSE, _T_TRUE, _T_INT, _T_NEGINT, _T_BYTES, _T_STR, _T_LIST, \
    _T_DICT, _T_TUPLE, _T_FLOAT = range(11)


#: single-byte cache: u8 and small-varint emission without a
#: struct.pack call each (the wire codec runs per message on the hot
#: path; these micro-ops showed up as whole percents of the cluster
#: bench wall)
_B1 = [bytes([i]) for i in range(256)]


class Encoder:
    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def u8(self, v: int) -> "Encoder":
        self._parts.append(_B1[v])
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v))
        return self

    def varint(self, v: int) -> "Encoder":
        """LEB128 unsigned varint (denc.h uses the same shape)."""
        assert v >= 0
        if v < 0x80:  # the overwhelmingly common case on this wire
            self._parts.append(_B1[v])
            return self
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self._parts.append(bytes(out))
        return self

    def blob(self, data: bytes) -> "Encoder":
        self.varint(len(data))
        # immutable bytes are referenced as-is (zero-copy); mutable
        # buffers (bytearray/memoryview) are defensively copied -- use
        # blob_ref to opt out of the copy under the no-mutation contract
        self._parts.append(data if type(data) is bytes else bytes(data))
        return self

    def blob_parts(self, parts) -> "Encoder":
        """Length-prefixed blob whose CONTENT is an already-encoded part
        list (e.g. another Encoder's :meth:`parts`): the parts are
        referenced, not joined -- how the messenger nests a wire message
        body into a transport frame with zero copies."""
        self.varint(sum(len(p) for p in parts))
        self._parts.extend(parts)
        return self

    def blob_ref(self, data) -> "Encoder":
        """Length-prefixed blob that REFERENCES the caller's buffer
        (no copy, even for mutable bytearray/memoryview/ndarray views).
        Contract: the caller must not mutate the buffer until the
        encoded record has been written out."""
        view = data if isinstance(data, memoryview) else memoryview(data)
        view = view.cast("B") if view.format != "B" else view
        self.varint(view.nbytes)
        self._parts.append(view)
        return self

    def string(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    def value(self, v: Any) -> "Encoder":
        """Tagged self-describing value
        (None/bool/int/bytes/str/list/tuple/dict)."""
        if v is None:
            self.u8(_T_NONE)
        elif v is True:
            self.u8(_T_TRUE)
        elif v is False:
            self.u8(_T_FALSE)
        elif type(v) is int:  # before the np.integer ABC walk: plain
            # ints are the hot case (versions, seqs, crc lists)
            if v >= 0:
                self.u8(_T_INT).varint(v)
            else:
                self.u8(_T_NEGINT).varint(-v)
        elif type(v) is bytes:
            self.u8(_T_BYTES).blob(v)
        elif type(v) is str:
            self.u8(_T_STR).string(v)
        elif isinstance(v, np.integer):
            self.value(int(v))
        elif isinstance(v, int):
            if v >= 0:
                self.u8(_T_INT).varint(v)
            else:
                self.u8(_T_NEGINT).varint(-v)
        elif isinstance(v, float):
            self.u8(_T_FLOAT)
            self._parts.append(struct.pack("<d", v))
        elif isinstance(v, (bytes, bytearray, memoryview)):
            self.u8(_T_BYTES).blob(bytes(v))
        elif isinstance(v, str):
            self.u8(_T_STR).string(v)
        elif isinstance(v, tuple):
            self.u8(_T_TUPLE).varint(len(v))
            for item in v:
                self.value(item)
        elif isinstance(v, list):
            self.u8(_T_LIST).varint(len(v))
            for item in v:
                self.value(item)
        elif isinstance(v, dict):
            self.u8(_T_DICT).varint(len(v))
            for k in v:
                if not isinstance(k, str):
                    raise TypeError(f"dict keys must be str, got {type(k)}")
                self.string(k)
                self.value(v[k])
        else:
            raise TypeError(f"unencodable type {type(v)}")
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def nbytes(self) -> int:
        """Total encoded length without joining."""
        return sum(len(p) for p in self._parts)

    def parts(self, small: int = 2048) -> List:
        """The encoded record as a buffer list for scatter-gather output
        (``writer.writelines`` / ``os.writev``).  Runs of parts smaller
        than ``small`` are joined so the vector stays short (tag bytes
        and varints collapse into one buffer between large blobs); large
        blobs are REFERENCED, never copied."""
        ps = self._parts
        if sum(map(len, ps)) <= small:
            # whole record below the scatter threshold: one join beats
            # any per-part bookkeeping (the hot shape -- sub-op frames)
            return [b"".join(ps)] if len(ps) > 1 else list(ps)
        out: List = []
        run: List[bytes] = []
        for p in ps:
            if len(p) < small:
                run.append(p if type(p) is bytes else bytes(p))
            else:
                if run:
                    out.append(run[0] if len(run) == 1 else b"".join(run))
                    run = []
                out.append(p)
        if run:
            out.append(run[0] if len(run) == 1 else b"".join(run))
        return out


class Decoder:
    def __init__(self, data: bytes, pos: int = 0) -> None:
        self._data = data
        self._pos = pos

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("decode past end of buffer")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        pos = self._pos
        if pos >= len(self._data):
            raise ValueError("decode past end of buffer")
        self._pos = pos + 1
        return self._data[pos]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def varint(self) -> int:
        data, pos = self._data, self._pos
        if pos < len(data) and not data[pos] & 0x80:  # 1-byte fast path
            self._pos = pos + 1
            return data[pos]
        v = 0
        shift = 0
        while True:
            b = self.u8()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 63:
                raise ValueError("varint too long")

    def blob(self) -> bytes:
        return self._take(self.varint())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def value(self) -> Any:
        tag = self.u8()
        # ordered by wire frequency: ints, blobs and strings dominate
        if tag == _T_INT:
            return self.varint()
        if tag == _T_BYTES:
            return self.blob()
        if tag == _T_STR:
            return self.string()
        if tag == _T_NONE:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_NEGINT:
            return -self.varint()
        if tag == _T_LIST:
            return [self.value() for _ in range(self.varint())]
        if tag == _T_TUPLE:
            return tuple(self.value() for _ in range(self.varint()))
        if tag == _T_DICT:
            return {self.string(): self.value() for _ in range(self.varint())}
        if tag == _T_FLOAT:
            return struct.unpack("<d", self._take(8))[0]
        raise ValueError(f"bad value tag {tag}")


def crc32c_parts(parts, crc: Optional[int] = None) -> int:
    """crc32c of the concatenation of ``parts`` WITHOUT concatenating:
    castagnoli chains, so ``crc(a||b) == crc32c(b, crc32c(a))``.  Pass
    ``crc`` to continue a digest already folded over earlier parts (the
    messenger caches each queued message's payload crc once and only
    folds the per-transmission tail on retransmit).

    A wire-tax cost center (``wire.crc32c``): runs once per burst
    element, nested inside the messenger's ``wire.crc_seal`` stage --
    exclusive accounting splits the digest from the seal bookkeeping."""
    with _PS_CRC:
        for p in parts:
            crc = crc32c(p) if crc is None else crc32c(p, crc)
        return crc32c(b"") if crc is None else crc


def frame(payload: bytes) -> bytes:
    """MAGIC | u32 len | u32 crc32c(payload) | payload."""
    return struct.pack("<III", _MAGIC, len(payload), crc32c(payload)) + payload


def frame_parts(parts, crc: Optional[int] = None) -> List:
    """Scatter-gather :func:`frame`: header + payload part list, no
    concatenation.  ``crc`` short-circuits the digest when the caller
    already holds crc32c over exactly these parts (cached per burst
    element -- the double-crc audit); when absent it is folded
    incrementally via :func:`crc32c_parts`."""
    length = sum(len(p) for p in parts)
    if crc is None:
        crc = crc32c_parts(parts)
    return [struct.pack("<III", _MAGIC, length, crc)] + list(parts)


def unframe(data: bytes, pos: int) -> Tuple[Optional[bytes], int]:
    """Decode one framed record at ``pos``.

    Returns (payload, next_pos); (None, pos) on a torn/corrupt/short record
    -- the caller treats that as end-of-log (crash-recovery semantics).
    """
    if pos + 12 > len(data):
        return None, pos
    magic, length, crc = struct.unpack_from("<III", data, pos)
    if magic != _MAGIC or pos + 12 + length > len(data):
        return None, pos
    payload = data[pos + 12 : pos + 12 + length]
    if crc32c(payload) != crc:
        return None, pos
    return payload, pos + 12 + length
