"""Binary encode/decode framework (denc-lite).

Reference: src/include/encoding.h (1364 LoC) / src/include/denc.h -- every
persistent or wire struct in the reference serializes through one small
framework with explicit little-endian integer widths, length-prefixed
blobs, and crc-guarded envelopes.  This is the same idea reduced to what
the TPU framework persists: journal records, KV log records and object
metadata.

Value model (self-describing, tagged):
  None, bool, int (u64/zigzag-s64), bytes, str, list, tuple,
  dict[str, value].  Lists and tuples round-trip as distinct types.

Framed records (``frame``/``unframe``) carry ``MAGIC | len | crc32c |
payload`` so torn tail writes after a crash are detected and discarded --
the role of the reference's per-entry crcs in the FileStore journal
(src/os/filestore/FileJournal.cc) and the message envelope crcs
(src/msg/Message.cc).
"""

from __future__ import annotations

import struct
from typing import Any, List, Optional, Tuple

import numpy as np

from ceph_tpu.native.gf_native import crc32c

_MAGIC = 0xCE9B10C5

# value tags
_T_NONE, _T_FALSE, _T_TRUE, _T_INT, _T_NEGINT, _T_BYTES, _T_STR, _T_LIST, \
    _T_DICT, _T_TUPLE, _T_FLOAT = range(11)


class Encoder:
    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v))
        return self

    def varint(self, v: int) -> "Encoder":
        """LEB128 unsigned varint (denc.h uses the same shape)."""
        assert v >= 0
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self._parts.append(bytes(out))
        return self

    def blob(self, data: bytes) -> "Encoder":
        self.varint(len(data))
        self._parts.append(bytes(data))
        return self

    def string(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    def value(self, v: Any) -> "Encoder":
        """Tagged self-describing value
        (None/bool/int/bytes/str/list/tuple/dict)."""
        if v is None:
            self.u8(_T_NONE)
        elif v is True:
            self.u8(_T_TRUE)
        elif v is False:
            self.u8(_T_FALSE)
        elif isinstance(v, np.integer):
            self.value(int(v))
        elif isinstance(v, int):
            if v >= 0:
                self.u8(_T_INT).varint(v)
            else:
                self.u8(_T_NEGINT).varint(-v)
        elif isinstance(v, float):
            self.u8(_T_FLOAT)
            self._parts.append(struct.pack("<d", v))
        elif isinstance(v, (bytes, bytearray, memoryview)):
            self.u8(_T_BYTES).blob(bytes(v))
        elif isinstance(v, str):
            self.u8(_T_STR).string(v)
        elif isinstance(v, tuple):
            self.u8(_T_TUPLE).varint(len(v))
            for item in v:
                self.value(item)
        elif isinstance(v, list):
            self.u8(_T_LIST).varint(len(v))
            for item in v:
                self.value(item)
        elif isinstance(v, dict):
            self.u8(_T_DICT).varint(len(v))
            for k in v:
                if not isinstance(k, str):
                    raise TypeError(f"dict keys must be str, got {type(k)}")
                self.string(k)
                self.value(v[k])
        else:
            raise TypeError(f"unencodable type {type(v)}")
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    def __init__(self, data: bytes, pos: int = 0) -> None:
        self._data = data
        self._pos = pos

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("decode past end of buffer")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def varint(self) -> int:
        v = 0
        shift = 0
        while True:
            b = self.u8()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 63:
                raise ValueError("varint too long")

    def blob(self) -> bytes:
        return self._take(self.varint())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def value(self) -> Any:
        tag = self.u8()
        if tag == _T_NONE:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            return self.varint()
        if tag == _T_NEGINT:
            return -self.varint()
        if tag == _T_BYTES:
            return self.blob()
        if tag == _T_STR:
            return self.string()
        if tag == _T_LIST:
            return [self.value() for _ in range(self.varint())]
        if tag == _T_TUPLE:
            return tuple(self.value() for _ in range(self.varint()))
        if tag == _T_DICT:
            return {self.string(): self.value() for _ in range(self.varint())}
        if tag == _T_FLOAT:
            return struct.unpack("<d", self._take(8))[0]
        raise ValueError(f"bad value tag {tag}")


def frame(payload: bytes) -> bytes:
    """MAGIC | u32 len | u32 crc32c(payload) | payload."""
    return struct.pack("<III", _MAGIC, len(payload), crc32c(payload)) + payload


def unframe(data: bytes, pos: int) -> Tuple[Optional[bytes], int]:
    """Decode one framed record at ``pos``.

    Returns (payload, next_pos); (None, pos) on a torn/corrupt/short record
    -- the caller treats that as end-of-log (crash-recovery semantics).
    """
    if pos + 12 > len(data):
        return None, pos
    magic, length, crc = struct.unpack_from("<III", data, pos)
    if magic != _MAGIC or pos + 12 + length > len(data):
        return None, pos
    payload = data[pos + 12 : pos + 12 + length]
    if crc32c(payload) != crc:
        return None, pos
    return payload, pos + 12 + length
