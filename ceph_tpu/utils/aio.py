"""Small asyncio utilities: executor-backed file I/O and task hygiene.

The daemons and CLI tools are fully async; builtin ``open`` in a
coroutine stalls every dispatch loop sharing the event loop (the
cephlint ``async-blocking-call`` rule).  These helpers route the few
file touches the async paths need (address maps, keyrings, CLI
payloads) through the default executor.

``log_task_exception`` is the done-callback half of the
``async-orphan-task`` discipline: a retained task whose exception is
never read still fails silently (asyncio only warns at GC time, if
ever); attaching this callback makes the failure visible the moment
the task dies.
"""

from __future__ import annotations

import asyncio
import json
import sys
from typing import Any, Optional


async def read_text(path: str) -> str:
    loop = asyncio.get_event_loop()

    def _read() -> str:
        with open(path) as f:
            return f.read()

    return await loop.run_in_executor(None, _read)


async def read_bytes(path: str) -> bytes:
    loop = asyncio.get_event_loop()

    def _read() -> bytes:
        with open(path, "rb") as f:
            return f.read()

    return await loop.run_in_executor(None, _read)


async def read_json(path: str) -> Any:
    return json.loads(await read_text(path))


async def write_text(path: str, data: str) -> None:
    loop = asyncio.get_event_loop()

    def _write() -> None:
        with open(path, "w") as f:
            f.write(data)

    await loop.run_in_executor(None, _write)


async def write_bytes(path: str, data: bytes) -> None:
    loop = asyncio.get_event_loop()

    def _write() -> None:
        with open(path, "wb") as f:
            f.write(data)

    await loop.run_in_executor(None, _write)


def log_task_exception(task: "asyncio.Task",
                       context: Optional[str] = None) -> None:
    """Done-callback: surface a task's unhandled exception on stderr
    (CancelledError is the normal shutdown path and stays silent)."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is None:
        return
    name = context or getattr(task, "get_name", lambda: repr(task))()
    print(f"task {name!r} died: {exc!r}", file=sys.stderr)
    import traceback

    traceback.print_exception(type(exc), exc, exc.__traceback__,
                              file=sys.stderr)
