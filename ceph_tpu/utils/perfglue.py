"""perfglue: CPU profiler glue, admin-socket triggered.

Reference: src/perfglue/cpu_profiler.cc -- the reference links
gperftools and exposes ``cpu_profiler start/stop/dump`` over the admin
socket.  The Python runtime's equivalent is cProfile: start/stop a
profiler around live daemon execution and dump the hottest functions,
all through the same admin-socket command the reference uses.
"""

from __future__ import annotations

import cProfile
import io
import pstats
from typing import Optional


class CpuProfiler:
    """One per daemon (the cpu_profiler command handler)."""

    def __init__(self):
        self._prof: Optional[cProfile.Profile] = None

    def handle_command(self, cmd: dict):
        action = cmd.get("action", "status")
        if action == "start":
            if self._prof is not None:
                return {"error": "profiler already running"}
            self._prof = cProfile.Profile()
            self._prof.enable()
            return {"status": "started"}
        if action == "stop":
            if self._prof is None:
                return {"error": "profiler not running"}
            self._prof.disable()
            buf = io.StringIO()
            stats = pstats.Stats(self._prof, stream=buf)
            stats.sort_stats("cumulative").print_stats(
                int(cmd.get("top", 20))
            )
            self._prof = None
            return {"status": "stopped", "report": buf.getvalue()}
        if action == "status":
            return {"running": self._prof is not None}
        return {"error": f"unknown action {action!r}"}


def register(asok, name: str = "cpu_profiler") -> CpuProfiler:
    """Attach a profiler to a daemon's admin socket
    (AdminSocket::register_command in global init, perfglue role)."""
    prof = CpuProfiler()
    asok.register(name, prof.handle_command)
    return prof
