"""Sampled, bounded distributed-trace spans (ZTracer/blkin analogue).

Reference: src/common/zipkin_trace.h:40 ZTracer::Trace -- the EC write
path carries child spans across daemons (ECBackend.cc:2003-2008
trace.init("ec sub write"), :931 trace.event("handle_sub_write")).

Round 16 rewrote the seed stub into the observability substrate the
batched data plane needs (docs/observability.md):

* **Sampling**: ``trace_mode`` off | sampled | full.  In sampled mode
  one in ``trace_sample_every`` root traces is real; the rest get the
  shared :data:`NULL_SPAN` whose every method is a no-op, so the
  unsampled fast path costs one counter increment and a modulo.  The
  decision travels WITH the trace: a daemon that receives a wire
  context creates real spans, one that receives none creates nothing
  -- no per-hop re-rolling, no half-sampled traces.
* **Batch fan-in spans**: when N ops ride one shared stage (a
  coalescer batch, a corked burst, a fused encode dispatch, a mesh
  SPMD dispatch, a recovery multi-read), the stage is ONE span linked
  as a child of all N op spans (``parent_ids``) with
  ``amortized_over=N``.  Each op's timeline decomposes the shared
  interval into its amortized compute share plus batch wait -- no
  per-op double-timing (see :func:`op_timeline`).
* **Wire context**: ``span.to_wire()`` is a tiny ``[trace_id,
  span_id]`` pair carried as a TRAILING optional field on message
  bodies (reqid-style, ``# cephlint: wire-optional`` in msg/wire.py),
  so spans stitch client -> primary -> sub-write/sub-read across
  daemons and pre-trace peers interop unchanged.
* **Bounded collection**: finished spans land in a ring of
  ``trace_keep`` plus a slowest-roots retention ring of
  ``trace_keep_slow`` (the optracker historic-ring discipline); the
  seed's grow-forever ``_finished`` list is gone.  Drops are counted.

Span ids are salted with the pid so traces stitched across real
daemon processes cannot collide; the in-process mini-cluster shares
this module and stitches for free.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

_MODES = ("off", "sampled", "full")

#: process-unique span id space: traces from different daemon
#: processes merge into one timeline without id collisions
_ID_BASE = (os.getpid() & 0x7FFF) << 44
_ids = itertools.count(_ID_BASE + 1)

_collector_lock = threading.Lock()
#: finished-span ring (bounded; trace_keep)
_finished: deque = deque(maxlen=256)
#: slowest finished ROOT spans, kept sorted by duration (trace_keep_slow)
_slow_roots: List["Span"] = []
#: started-but-unfinished real spans: id -> name (the ci smoke and the
#: trace-span-unfinished lint rule's runtime counterpart)
_live: Dict[int, str] = {}
_counters = {"finished": 0, "dropped": 0, "sampled_roots": 0,
             "unsampled_roots": 0, "live_overflow": 0}
#: hard cap on the live map so leaked spans cannot grow state forever
_LIVE_CAP = 4096

#: lazy-cached knobs (a per-op config lock acquisition would be real
#: overhead on the unsampled path; refresh via configure())
_mode: Optional[str] = None
_sample_every = 64
_keep_slow = 64
_sample_tick = 0

#: legacy surface (pre-round-16 callers used trace.enable/enabled)
enabled = False

#: the active span of THIS task (client ops run as their own tasks, so
#: contextvars keep concurrent ops' spans apart without threading a
#: parameter through every strategy signature -- the _OP_REQID pattern)
_CURRENT: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("ceph_tpu_trace_span", default=None)


def _load_config() -> None:
    global _mode, _sample_every, _keep_slow
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    _mode = str(cfg.get_val("trace_mode"))
    if _mode not in _MODES:
        _mode = "off"
    _sample_every = max(1, int(cfg.get_val("trace_sample_every")))
    _keep_slow = max(1, int(cfg.get_val("trace_keep_slow")))
    keep = max(1, int(cfg.get_val("trace_keep")))
    with _collector_lock:
        if _finished.maxlen != keep:
            _resize_ring(keep)


def _resize_ring(keep: int) -> None:
    global _finished
    old = list(_finished)
    _finished = deque(old[-keep:], maxlen=keep)


def mode() -> str:
    if _mode is None:
        _load_config()
    return _mode  # type: ignore[return-value]


def configure(mode: Optional[str] = None,
              sample_every: Optional[int] = None,
              keep: Optional[int] = None,
              keep_slow: Optional[int] = None) -> None:
    """Set tracing knobs at runtime (and push them into the config so
    ``config show`` agrees); None leaves a knob alone."""
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    if mode is not None:
        if mode not in _MODES:
            raise ValueError(f"bad trace mode {mode!r}")
        cfg.set_val("trace_mode", mode)
    if sample_every is not None:
        cfg.set_val("trace_sample_every", int(sample_every))
    if keep is not None:
        cfg.set_val("trace_keep", int(keep))
    if keep_slow is not None:
        cfg.set_val("trace_keep_slow", int(keep_slow))
    _load_config()
    global enabled
    enabled = _mode != "off"


def enable(on: bool = True) -> None:
    """Legacy toggle: ``True`` = full tracing, ``False`` = off (and the
    collector clears, as the seed behavior promised)."""
    configure(mode="full" if on else "off")
    if not on:
        clear()


def clear() -> None:
    with _collector_lock:
        _finished.clear()
        _slow_roots.clear()
        _live.clear()
        for key in _counters:
            _counters[key] = 0


def status() -> dict:
    m = mode()  # may lazily load config (takes the collector lock)
    with _collector_lock:
        return {
            "mode": m,
            "sample_every": _sample_every,
            "keep": _finished.maxlen,
            "keep_slow": _keep_slow,
            "finished": _counters["finished"],
            "dropped": _counters["dropped"],
            "sampled_roots": _counters["sampled_roots"],
            "unsampled_roots": _counters["unsampled_roots"],
            "unfinished": len(_live),
        }


def unfinished_count() -> int:
    """Started-but-unfinished real spans right now (0 after a quiesced
    workload -- the ci_lint traced-op smoke gates on this)."""
    with _collector_lock:
        return len(_live)


def unfinished_names() -> List[str]:
    with _collector_lock:
        return sorted(set(_live.values()))


class _NullSpan:
    """The unsampled span: every operation a no-op, one shared
    instance.  Truth-testing is False so ``if span:`` gates work."""

    __slots__ = ()
    sampled = False
    span_id = 0
    trace_id = 0
    parent_ids: Tuple[int, ...] = ()
    amortized_over = 1
    events: List[tuple] = []
    tags: Dict[str, object] = {}

    def event(self, name: str, t: Optional[float] = None) -> None:
        pass

    def tag_set(self, key: str, value) -> None:
        pass

    def link(self, parent: "Span") -> None:
        pass

    def child(self, name: str) -> "_NullSpan":
        return self

    def finish(self) -> None:
        pass

    def to_wire(self) -> None:
        return None

    @property
    def duration(self) -> float:
        return 0.0

    def __bool__(self) -> bool:
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One timed span.  ``parent_ids`` is a TUPLE: a batch fan-in span
    is the child of every op span whose work rode the shared stage."""

    __slots__ = (
        "name", "span_id", "trace_id", "parent_ids", "start", "wall",
        "end", "events", "tags", "amortized_over",
    )
    sampled = True

    def __init__(self, name: str, parent: Optional["Span"] = None,
                 trace_id: Optional[int] = None,
                 parent_ids: Sequence[int] = (),
                 t0: Optional[float] = None):
        self.name = name
        self.span_id = next(_ids)
        if parent is not None and parent.sampled:
            self.parent_ids: Tuple[int, ...] = (parent.span_id,)
            self.trace_id = parent.trace_id
        else:
            self.parent_ids = tuple(parent_ids)
            self.trace_id = trace_id if trace_id is not None \
                else self.span_id
        # t0 backdates the span start (a monotonic stamp taken before
        # the span object existed, e.g. queue entry) so queue wait is
        # attributed without allocating a span per queued op
        self.start = t0 if t0 is not None else time.monotonic()
        self.wall = time.time()
        self.end = 0.0
        self.events: List[tuple] = []
        self.tags: Dict[str, object] = {}
        self.amortized_over = 1
        with _collector_lock:
            if len(_live) >= _LIVE_CAP:
                _live.pop(next(iter(_live)), None)
                _counters["live_overflow"] += 1
            _live[self.span_id] = name

    # -- recording ---------------------------------------------------------

    def event(self, name: str, t: Optional[float] = None) -> None:
        """Timestamped event; ``t`` backdates it (a monotonic stamp
        taken before the span existed, e.g. enqueue time)."""
        self.events.append(
            ((t if t is not None else time.monotonic()) - self.start, name)
        )

    def tag_set(self, key: str, value) -> None:
        self.tags[key] = value

    def link(self, parent: "Span") -> None:
        """Fan-in: make this span a child of one more op span."""
        if parent.sampled and parent.span_id not in self.parent_ids:
            self.parent_ids = self.parent_ids + (parent.span_id,)
            if self.trace_id == self.span_id:
                self.trace_id = parent.trace_id

    def child(self, name: str) -> "Span":
        return Span(name, parent=self)

    def finish(self) -> None:
        if self.end:
            return  # idempotent: double-finish must not double-collect
        self.end = time.monotonic()
        with _collector_lock:
            _live.pop(self.span_id, None)
            if len(_finished) == _finished.maxlen:
                _counters["dropped"] += 1
            _finished.append(self)
            _counters["finished"] += 1
            if not self.parent_ids:
                # slowest-roots retention: the worst traces survive the
                # ring even under churn (optracker discipline)
                _slow_roots.append(self)
                _slow_roots.sort(key=lambda s: -s.duration)
                del _slow_roots[_keep_slow:]

    @property
    def duration(self) -> float:
        return (self.end or time.monotonic()) - self.start

    def to_wire(self) -> List[int]:
        """The on-the-wire context: tiny, trailing-field friendly."""
        return [self.trace_id, self.span_id]

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            # legacy single-parent view + the fan-in truth
            "parent_id": self.parent_ids[0] if self.parent_ids else 0,
            "parent_ids": list(self.parent_ids),
            "name": self.name,
            "start": self.wall,
            "duration_ms": (self.end - self.start) * 1000
            if self.end else None,
            "events": [name for _t, name in self.events],
            "timeline": [(round(t * 1000, 6), name)
                         for t, name in self.events],
            "tags": dict(self.tags),
            "amortized_over": self.amortized_over,
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


# -- creation ---------------------------------------------------------------

def _sample_root() -> bool:
    global _sample_tick
    m = mode()
    if m == "off":
        return False
    if m == "full":
        _counters["sampled_roots"] += 1
        return True
    _sample_tick += 1
    hit = _sample_tick % _sample_every == 0
    _counters["sampled_roots" if hit else "unsampled_roots"] += 1
    return hit


def new_trace(name: str):
    """Root span of a new trace -- or :data:`NULL_SPAN` when this trace
    loses the sampling roll (the decision then travels with the
    context: unsampled ops carry no wire field and downstream daemons
    spend nothing)."""
    if not _sample_root():
        return NULL_SPAN
    return Span(name)


def join(ctx, name: str, t0: Optional[float] = None):
    """Adopt a wire context: a child span of the remote parent.  A
    None/absent context (unsampled trace or pre-trace peer) costs one
    comparison.  ``t0`` backdates the span start (queue entry)."""
    if ctx is None or mode() == "off":
        return NULL_SPAN
    try:
        trace_id, parent_id = int(ctx[0]), int(ctx[1])
    except (TypeError, ValueError, IndexError):
        return NULL_SPAN
    return Span(name, trace_id=trace_id, parent_ids=(parent_id,), t0=t0)


def batch_span(name: str, parents: Sequence[object]):
    """ONE span for a stage shared by N ops (coalescer batch, fused
    dispatch, corked burst, mesh SPMD dispatch, recovery multi-read):
    child of every sampled parent, ``amortized_over`` = N so per-op
    timelines can claim ``duration / N`` with no double-timing.  With
    zero sampled parents the stage records nothing."""
    real = [p for p in parents if getattr(p, "sampled", False)]
    if not real:
        return NULL_SPAN
    span = Span(name, trace_id=real[0].trace_id,
                parent_ids=tuple(p.span_id for p in real))
    span.amortized_over = max(1, len(parents))
    for p in real:
        # let each op's timeline find its shared stage
        p.tag_set(f"fanin:{name}", span.span_id)
    return span


# -- task-scoped current span ----------------------------------------------

def current():
    """The active span of this task (NULL_SPAN when none)."""
    return _CURRENT.get() or NULL_SPAN


def current_wire():
    span = _CURRENT.get()
    return span.to_wire() if span is not None and span.sampled else None


class use_span:
    """Scope ``span`` as the task-current span (restores on exit; the
    span itself is NOT finished -- pair with ``with span`` when the
    scope is also the span's lifetime)."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span

    def __enter__(self):
        self._token = _CURRENT.set(
            self._span if getattr(self._span, "sampled", False) else None)
        return self._span

    def __exit__(self, *exc):
        _CURRENT.reset(self._token)
        return False


def event(name: str) -> None:
    """Event on the task-current span (no-op unsampled)."""
    span = _CURRENT.get()
    if span is not None:
        span.event(name)


def tag(key: str, value) -> None:
    span = _CURRENT.get()
    if span is not None:
        span.tag_set(key, value)


# -- collection / forensics -------------------------------------------------

def dump() -> List[dict]:
    with _collector_lock:
        return [s.to_dict() for s in _finished]


def dump_trace(trace_id: int) -> List[dict]:
    """Every collected span of one trace, parents before children where
    the ring preserved order."""
    with _collector_lock:
        return [s.to_dict() for s in _finished if s.trace_id == trace_id]


def dump_slow(limit: Optional[int] = None) -> List[dict]:
    """Slowest retained root spans, worst first."""
    with _collector_lock:
        roots = list(_slow_roots[: limit or _keep_slow])
    return [s.to_dict() for s in roots]


def find_span(span_id: int) -> Optional["Span"]:
    with _collector_lock:
        for s in _finished:
            if s.span_id == span_id:
                return s
    return None


#: friendly names for adjacent-event intervals in an op timeline; any
#: unlisted pair reads "<a>-><b>" (still summing exactly)
_SEGMENT_NAMES = {
    # span start is backdated to queue entry (trace.join t0)
    ("start", "dequeued"): "queue_wait",
    ("queued", "dequeued"): "queue_wait",
    ("dequeued", "started"): "admit_wait",
    ("started", "encode_submit"): "prepare",
    ("encode_submit", "encode_done"): "batch_encode",
    ("decode_submit", "decode_done"): "batch_decode",
    ("encode_done", "fanout_sent"): "fanout_prep",
    ("fanout_sent", "commit"): "wire_commit",
    ("commit", "replied"): "ack",
    ("gather_sent", "gather_done"): "wire_gather",
}


def op_timeline(span) -> dict:
    """Decompose one op span into named latency segments.

    Segments are the deltas between adjacent recorded events (plus a
    leading start gap and trailing finish gap), so they sum EXACTLY to
    the span's end-to-end duration.  A batch interval (the op waited on
    a fan-in stage it shares with N-1 other ops) is split into the op's
    amortized compute share (``fan-in duration / N``, from the linked
    batch span when still collected) and the residual batch wait --
    amortized shares across all N ops sum to the stage once."""
    if isinstance(span, int):
        span = find_span(span)
    if span is None or not getattr(span, "sampled", False):
        return {"segments": [], "total_ms": 0.0}
    total = (span.end or time.monotonic()) - span.start
    points = [(0.0, "start")] + sorted(span.events) + [(total, "end")]
    segments: List[dict] = []
    for (t0, a), (t1, b) in zip(points, points[1:]):
        ms = max(0.0, (t1 - t0) * 1000)
        if ms == 0.0 and (a, b) not in _SEGMENT_NAMES:
            continue
        name = _SEGMENT_NAMES.get((a, b), f"{a}->{b}")
        seg = {"segment": name, "ms": round(ms, 6)}
        if name in ("batch_encode", "batch_decode"):
            fanin_id = span.tags.get("fanin:" + name)
            fanin = find_span(fanin_id) if fanin_id else None
            if fanin is not None:
                share = (fanin.duration * 1000 /
                         max(1, fanin.amortized_over))
                seg["amortized_share_ms"] = round(min(share, ms), 6)
                seg["batch_wait_ms"] = round(
                    max(0.0, ms - seg["amortized_share_ms"]), 6)
                seg["batch_n"] = fanin.amortized_over
        segments.append(seg)
    return {
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "name": span.name,
        "total_ms": round(total * 1000, 6),
        "segments": segments,
    }
