"""Lightweight distributed-trace spans (ZTracer/blkin analogue).

Reference: src/common/zipkin_trace.h:40 ZTracer::Trace -- the EC write path
carries per-shard child spans (ECBackend.cc:2003-2008 trace.init("ec sub
write"), :931 trace.event("handle_sub_write")).  Here: spans with parent
links, timed events, and an in-memory collector that can dump a trace tree
(the role of the zipkin collector for tests/debugging).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

_ids = itertools.count(1)
_collector_lock = threading.Lock()
_finished: List["Span"] = []
enabled = False


def enable(on: bool = True) -> None:
    global enabled
    enabled = on
    if not on:
        with _collector_lock:
            _finished.clear()


class Span:
    __slots__ = (
        "name", "span_id", "parent_id", "trace_id", "start", "end", "events"
    )

    def __init__(self, name: str, parent: Optional["Span"] = None):
        self.name = name
        self.span_id = next(_ids)
        self.parent_id = parent.span_id if parent else 0
        self.trace_id = parent.trace_id if parent else self.span_id
        self.start = time.time()
        self.end = 0.0
        self.events: List[tuple] = []

    def event(self, name: str) -> None:
        if enabled:
            self.events.append((time.time(), name))

    def child(self, name: str) -> "Span":
        return Span(name, parent=self)

    def finish(self) -> None:
        self.end = time.time()
        if enabled:
            with _collector_lock:
                _finished.append(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


def new_trace(name: str) -> Span:
    return Span(name)


def dump() -> List[dict]:
    with _collector_lock:
        return [
            {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "name": s.name,
                "duration_ms": (s.end - s.start) * 1000 if s.end else None,
                "events": [name for _, name in s.events],
            }
            for s in _finished
        ]
