"""Perf counters + admin-socket-style dump (PerfCounters equivalent).

Reference: src/common/perf_counters.h:53 PerfCountersBuilder and the
admin-socket ``perf dump`` command (src/common/admin_socket.cc).  Counters
are typed (counts, sums, time averages); every subsystem instance registers
in a process-wide collection that ``dump()`` serializes like
``ceph daemon <sock> perf dump``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Dict


class PerfCounters:
    _collection: Dict[str, "PerfCounters"] = {}
    _collection_lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        with PerfCounters._collection_lock:
            PerfCounters._collection[name] = self

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[key] += amount

    def hwm(self, key: str, value: int) -> None:
        """High-water-mark counter: keeps the max ever reported (the
        reference's PERFCOUNTER_U64 gauges used as peaks, e.g. resident
        cache-tier bytes)."""
        with self._lock:
            if value > self._counters[key]:
                self._counters[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        """Time/average counter (latency style)."""
        with self._lock:
            self._sums[key] += seconds
            self._counts[key] += 1

    def time(self, key: str):
        """Context manager measuring a code block into a tinc counter."""
        outer = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                outer.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            for key in self._sums:
                out[key] = {
                    "avgcount": self._counts[key],
                    "sum": self._sums[key],
                }
            return out

    @classmethod
    def dump(cls) -> str:
        """The `perf dump` admin-socket command."""
        with cls._collection_lock:
            return json.dumps(
                {name: pc.snapshot() for name, pc in cls._collection.items()},
                indent=2,
                sort_keys=True,
            )

    @classmethod
    def reset_all(cls) -> None:
        with cls._collection_lock:
            cls._collection.clear()
            PerfHistogram._collection.clear()


class HistogramAxis:
    """One axis of a 2D perf histogram (src/perf_histogram.h
    axis_config_d): ``scale`` is "linear" or "log2"; values below
    ``min`` land in bucket 0, values past the last bucket in the last
    (the reference's underflow/overflow buckets)."""

    def __init__(self, name: str, min_value: int, quant_size: int,
                 buckets: int, scale: str = "log2"):
        if scale not in ("linear", "log2"):
            raise ValueError(f"unknown axis scale {scale!r}")
        self.name = name
        self.min = min_value
        self.quant = quant_size
        self.buckets = buckets
        self.scale = scale

    def bucket_for(self, value: float) -> int:
        if value < self.min:
            return 0
        off = value - self.min
        if self.scale == "linear":
            b = 1 + int(off // self.quant)
        else:
            # closed form of the doubling walk (b doublings cover
            # quant*(2^b - 1)): O(1) -- this runs on every data-path
            # latency observation, a Python loop here was measurable
            b = (int(off) // self.quant + 1).bit_length()
        return min(b, self.buckets - 1)

    def upper_bounds(self) -> list:
        """Inclusive upper bound of every bucket but the last (whose
        bound is +Inf) -- the prometheus ``le`` values this axis maps
        onto.  Bucket 0 is the underflow bucket (< min)."""
        if self.scale == "linear":
            return [self.min + self.quant * b
                    for b in range(self.buckets - 1)]
        out = [self.min]
        acc = 0
        for b in range(1, self.buckets - 1):
            acc += self.quant * (2 ** (b - 1))
            out.append(self.min + acc)
        return out

    def to_dict(self) -> dict:
        return {"name": self.name, "min": self.min, "quant_size": self.quant,
                "buckets": self.buckets, "scale_type": self.scale}


class PerfHistogram:
    """2D counter grid (src/perf_histogram.h PerfHistogram<2>): e.g.
    request latency x request size, dumped by ``perf histogram dump``.
    Cells are x-major."""

    _collection: Dict[str, "PerfHistogram"] = {}

    def __init__(self, name: str, x: HistogramAxis, y: HistogramAxis):
        self.name = name
        self.x = x
        self.y = y
        self._lock = threading.Lock()
        self._values = [0] * (x.buckets * y.buckets)
        #: running sum of raw x observations (the prometheus ``_sum``
        #: series; the grid alone only preserves bucketed counts)
        self._x_sum = 0.0
        self._count = 0
        with PerfCounters._collection_lock:
            PerfHistogram._collection[name] = self

    def inc(self, x_value: float, y_value: float, amount: int = 1) -> None:
        bx = self.x.bucket_for(x_value)
        by = self.y.bucket_for(y_value)
        with self._lock:
            self._values[bx * self.y.buckets + by] += amount
            self._x_sum += x_value * amount
            self._count += amount

    def inc_many(self, x_value: float, y_values, amount: int = 1) -> None:
        """Batch form of :meth:`inc` for one shared x observation over a
        run of y values (the OSD's array-batched op path): the x bucket
        is computed once and the whole run folds in under ONE lock
        acquisition instead of one per observation."""
        base = self.x.bucket_for(x_value) * self.y.buckets
        bucket_y = self.y.bucket_for
        n = 0
        with self._lock:
            for y in y_values:
                self._values[base + bucket_y(y)] += amount
                n += 1
            self._x_sum += x_value * amount * n
            self._count += amount * n

    def inc_pairs(self, pairs) -> None:
        """Batch form of :meth:`inc` for (x, y) observation pairs: one
        lock acquisition for the whole run."""
        bucket_x = self.x.bucket_for
        bucket_y = self.y.bucket_for
        yb = self.y.buckets
        with self._lock:
            n = 0
            for x, y in pairs:
                self._values[bucket_x(x) * yb + bucket_y(y)] += 1
                self._x_sum += x
                n += 1
            self._count += n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "axes": [self.x.to_dict(), self.y.to_dict()],
                "values": list(self._values),
                "x_sum": self._x_sum,
                "count": self._count,
            }

    def x_marginal(self) -> list:
        """Per-x-bucket counts summed over the y axis (the 1-D latency
        distribution a prometheus histogram series exposes)."""
        with self._lock:
            vals = list(self._values)
        yb = self.y.buckets
        return [sum(vals[bx * yb:(bx + 1) * yb])
                for bx in range(self.x.buckets)]

    @classmethod
    def get_or_create(cls, name: str, x_factory, y_factory
                      ) -> "PerfHistogram":
        """Idempotent registration: per-stage latency observers share
        one histogram per (daemon, stage) name no matter which engine
        touches it first."""
        with PerfCounters._collection_lock:
            h = cls._collection.get(name)
        if h is not None:
            return h
        cls(name, x_factory(), y_factory())
        with PerfCounters._collection_lock:
            return cls._collection[name]

    @classmethod
    def dump(cls) -> str:
        """The ``perf histogram dump`` admin-socket command."""
        with PerfCounters._collection_lock:
            return json.dumps(
                {name: h.snapshot() for name, h in cls._collection.items()},
                indent=2, sort_keys=True,
            )


def stage_histogram(name: str) -> PerfHistogram:
    """The shared per-stage latency observer: a latency(usec, log2) x
    size(bytes, log2) grid under ``name`` (one per daemon per stage --
    queue-wait, dispatch, wire-rtt, ack-lag, tier hit/miss read), the
    PerfHistogram the prometheus module exposes as real
    ``_bucket``/``_sum``/``_count`` series."""
    return PerfHistogram.get_or_create(
        name,
        lambda: HistogramAxis("latency_usec", 0, 64, 32, "log2"),
        lambda: HistogramAxis("size_bytes", 0, 512, 24, "log2"),
    )


def histogram_marginals(prefix: str = "") -> Dict[str, dict]:
    """Per-histogram x-axis marginal + bounds + sum/count, the compact
    form MgrReport frames ship (a full 2-D grid per report would be
    ~25x the bytes for no exposition gain: the prometheus series only
    ever render the latency marginal)."""
    with PerfCounters._collection_lock:
        hists = list(PerfHistogram._collection.items())
    out: Dict[str, dict] = {}
    for name, h in hists:
        if prefix and not name.startswith(prefix):
            continue
        snap = h.snapshot()
        out[name] = {
            "bounds": h.x.upper_bounds(),
            "marginal": h.x_marginal(),
            "sum": snap["x_sum"],
            "count": snap["count"],
        }
    return out


def histograms_prometheus_text() -> str:
    """Every registered PerfHistogram as prometheus histogram series:
    cumulative ``_bucket{le=...}`` over the x (latency) marginal, plus
    ``_sum`` (raw x sum) and ``_count``.  Instances named
    ``<daemon>.<stage>`` (daemon like ``osd.0`` / ``client``) share one
    metric family per stage with a ``ceph_daemon`` label."""
    with PerfCounters._collection_lock:
        hists = list(PerfHistogram._collection.items())
    families: Dict[str, list] = {}
    for name, h in sorted(hists):
        parts = name.split(".")
        if len(parts) >= 3 and parts[0] == "osd" and parts[1].isdigit():
            daemon, family = f"{parts[0]}.{parts[1]}", ".".join(parts[2:])
        elif len(parts) >= 2:
            daemon, family = parts[0], ".".join(parts[1:])
        else:
            daemon, family = "", name
        metric = "ceph_hist_" + "".join(
            c if c.isalnum() else "_" for c in family)
        families.setdefault(metric, []).append((daemon, h))
    lines = []
    for metric in sorted(families):
        lines.append(f"# HELP {metric} per-stage latency histogram "
                     "(PerfHistogram x-axis marginal; le in the axis "
                     "unit)")
        lines.append(f"# TYPE {metric} histogram")
        for daemon, h in families[metric]:
            label = f'{{ceph_daemon="{daemon}",le=' if daemon \
                else "{le="
            marginal = h.x_marginal()
            bounds = h.x.upper_bounds()
            cum = 0
            for ub, count in zip(bounds, marginal):
                cum += count
                lines.append(f'{metric}_bucket{label}"{ub}"}} {cum}')
            cum += sum(marginal[len(bounds):])
            lines.append(f'{metric}_bucket{label}"+Inf"}} {cum}')
            snap = h.snapshot()
            tail = f'{{ceph_daemon="{daemon}"}}' if daemon else ""
            lines.append(f"{metric}_sum{tail} {snap['x_sum']}")
            lines.append(f"{metric}_count{tail} {snap['count']}")
    return "\n".join(lines)
