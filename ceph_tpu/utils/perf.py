"""Perf counters + admin-socket-style dump (PerfCounters equivalent).

Reference: src/common/perf_counters.h:53 PerfCountersBuilder and the
admin-socket ``perf dump`` command (src/common/admin_socket.cc).  Counters
are typed (counts, sums, time averages); every subsystem instance registers
in a process-wide collection that ``dump()`` serializes like
``ceph daemon <sock> perf dump``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Dict


class PerfCounters:
    _collection: Dict[str, "PerfCounters"] = {}
    _collection_lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        with PerfCounters._collection_lock:
            PerfCounters._collection[name] = self

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[key] += amount

    def hwm(self, key: str, value: int) -> None:
        """High-water-mark counter: keeps the max ever reported (the
        reference's PERFCOUNTER_U64 gauges used as peaks, e.g. resident
        cache-tier bytes)."""
        with self._lock:
            if value > self._counters[key]:
                self._counters[key] = value

    def tinc(self, key: str, seconds: float) -> None:
        """Time/average counter (latency style)."""
        with self._lock:
            self._sums[key] += seconds
            self._counts[key] += 1

    def time(self, key: str):
        """Context manager measuring a code block into a tinc counter."""
        outer = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                outer.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            for key in self._sums:
                out[key] = {
                    "avgcount": self._counts[key],
                    "sum": self._sums[key],
                }
            return out

    @classmethod
    def dump(cls) -> str:
        """The `perf dump` admin-socket command."""
        with cls._collection_lock:
            return json.dumps(
                {name: pc.snapshot() for name, pc in cls._collection.items()},
                indent=2,
                sort_keys=True,
            )

    @classmethod
    def reset_all(cls) -> None:
        with cls._collection_lock:
            cls._collection.clear()
            PerfHistogram._collection.clear()


class HistogramAxis:
    """One axis of a 2D perf histogram (src/perf_histogram.h
    axis_config_d): ``scale`` is "linear" or "log2"; values below
    ``min`` land in bucket 0, values past the last bucket in the last
    (the reference's underflow/overflow buckets)."""

    def __init__(self, name: str, min_value: int, quant_size: int,
                 buckets: int, scale: str = "log2"):
        if scale not in ("linear", "log2"):
            raise ValueError(f"unknown axis scale {scale!r}")
        self.name = name
        self.min = min_value
        self.quant = quant_size
        self.buckets = buckets
        self.scale = scale

    def bucket_for(self, value: float) -> int:
        if value < self.min:
            return 0
        off = value - self.min
        if self.scale == "linear":
            b = 1 + int(off // self.quant)
        else:
            b = 1
            span = self.quant
            while off >= span and b < self.buckets - 1:
                off -= span
                span *= 2
                b += 1
        return min(b, self.buckets - 1)

    def to_dict(self) -> dict:
        return {"name": self.name, "min": self.min, "quant_size": self.quant,
                "buckets": self.buckets, "scale_type": self.scale}


class PerfHistogram:
    """2D counter grid (src/perf_histogram.h PerfHistogram<2>): e.g.
    request latency x request size, dumped by ``perf histogram dump``.
    Cells are x-major."""

    _collection: Dict[str, "PerfHistogram"] = {}

    def __init__(self, name: str, x: HistogramAxis, y: HistogramAxis):
        self.name = name
        self.x = x
        self.y = y
        self._lock = threading.Lock()
        self._values = [0] * (x.buckets * y.buckets)
        with PerfCounters._collection_lock:
            PerfHistogram._collection[name] = self

    def inc(self, x_value: float, y_value: float, amount: int = 1) -> None:
        bx = self.x.bucket_for(x_value)
        by = self.y.bucket_for(y_value)
        with self._lock:
            self._values[bx * self.y.buckets + by] += amount

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "axes": [self.x.to_dict(), self.y.to_dict()],
                "values": list(self._values),
            }

    @classmethod
    def dump(cls) -> str:
        """The ``perf histogram dump`` admin-socket command."""
        with PerfCounters._collection_lock:
            return json.dumps(
                {name: h.snapshot() for name, h in cls._collection.items()},
                indent=2, sort_keys=True,
            )
