"""Perf counters + admin-socket-style dump (PerfCounters equivalent).

Reference: src/common/perf_counters.h:53 PerfCountersBuilder and the
admin-socket ``perf dump`` command (src/common/admin_socket.cc).  Counters
are typed (counts, sums, time averages); every subsystem instance registers
in a process-wide collection that ``dump()`` serializes like
``ceph daemon <sock> perf dump``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Dict


class PerfCounters:
    _collection: Dict[str, "PerfCounters"] = {}
    _collection_lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        with PerfCounters._collection_lock:
            PerfCounters._collection[name] = self

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[key] += amount

    def tinc(self, key: str, seconds: float) -> None:
        """Time/average counter (latency style)."""
        with self._lock:
            self._sums[key] += seconds
            self._counts[key] += 1

    def time(self, key: str):
        """Context manager measuring a code block into a tinc counter."""
        outer = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                outer.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counters)
            for key in self._sums:
                out[key] = {
                    "avgcount": self._counts[key],
                    "sum": self._sums[key],
                }
            return out

    @classmethod
    def dump(cls) -> str:
        """The `perf dump` admin-socket command."""
        with cls._collection_lock:
            return json.dumps(
                {name: pc.snapshot() for name, pc in cls._collection.items()},
                indent=2,
                sort_keys=True,
            )

    @classmethod
    def reset_all(cls) -> None:
        with cls._collection_lock:
            cls._collection.clear()
