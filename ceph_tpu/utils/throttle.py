"""Throttle: budgeted flow control (reference: src/common/Throttle.{h,cc}).

The reference's Throttle is a counted budget: ``get(c)`` blocks while
the budget is exhausted (in FIFO order -- each waiter queues a cond),
``put(c)`` returns budget and wakes waiters; ``get_or_fail`` is the
non-blocking form.  Used all over the daemons: messenger dispatch
byte caps (osd_client_message_size_cap), journal bytes, objecter
in-flight ops.  BackoffThrottle adds a probabilistic delay ramp as the
budget approaches full instead of a hard wall.

Async re-design: waiters are asyncio futures served strictly FIFO, so
one large request cannot be starved by a stream of small ones (the
reference has the same fairness via its cond queue).
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Deque, Optional, Tuple


class Throttle:
    def __init__(self, name: str, max_budget: int):
        self.name = name
        self.max = max_budget
        self.count = 0
        self._waiters: Deque[Tuple[int, asyncio.Future]] = deque()
        # observability (PerfCounters-lite, matching l_throttle_*)
        self.n_gets = 0
        self.n_waits = 0

    def _should_wait(self, c: int) -> bool:
        if self.max <= 0:
            return False  # unlimited
        # a request larger than max is allowed through alone (the
        # reference admits oversized requests when the budget is empty)
        if c >= self.max:
            return self.count > 0
        return self.count + c > self.max

    def _wake(self) -> None:
        while self._waiters:
            c, fut = self._waiters[0]
            if self._should_wait(c):
                break
            self._waiters.popleft()
            if not fut.done():
                self.count += c
                fut.set_result(True)

    async def get(self, c: int = 1) -> None:
        """Take ``c`` budget; FIFO-blocks while exhausted."""
        self.n_gets += 1
        if not self._waiters and not self._should_wait(c):
            self.count += c
            return
        self.n_waits += 1
        fut = asyncio.get_event_loop().create_future()
        self._waiters.append((c, fut))
        try:
            await fut
        except asyncio.CancelledError:
            if fut.cancelled():
                # never granted (task cancellation cancels the future
                # itself): just dequeue -- putting here would return
                # budget that was never taken and over-admit past max
                try:
                    self._waiters.remove((c, fut))
                except ValueError:
                    pass
                self._wake()  # we may have been the FIFO head blocking
                # smaller requests behind us
            else:
                # granted (set_result) between the cancel and here
                self.put(c)
            raise

    def get_or_fail(self, c: int = 1) -> bool:
        self.n_gets += 1
        if self._waiters or self._should_wait(c):
            return False
        self.count += c
        return True

    def put(self, c: int = 1) -> None:
        self.count = max(0, self.count - c)
        self._wake()

    def set_max(self, m: int) -> None:
        self.max = m
        self._wake()

    def past_midpoint(self) -> bool:
        return self.max > 0 and self.count >= self.max // 2


class BackoffThrottle:
    """Delay-ramp throttle (src/common/Throttle.h BackoffThrottle):
    below ``low`` utilization no delay; between low and high the delay
    ramps linearly to ``max_delay``; above high it's the full delay.
    Used by BlueStore to pace deferred writes without a hard wall."""

    def __init__(self, name: str, max_budget: int,
                 low: float = 0.5, high: float = 0.9,
                 max_delay: float = 0.05):
        self.name = name
        self.max = max_budget
        self.count = 0
        self.low = low
        self.high = high
        self.max_delay = max_delay

    def _delay(self) -> float:
        if self.max <= 0:
            return 0.0
        util = self.count / self.max
        if util < self.low:
            return 0.0
        if util >= self.high:
            return self.max_delay
        return self.max_delay * (util - self.low) / (self.high - self.low)

    async def get(self, c: int = 1) -> float:
        d = self._delay()
        if d > 0:
            await asyncio.sleep(d)
        self.count += c
        return d

    def put(self, c: int = 1) -> None:
        self.count = max(0, self.count - c)
