"""Admin socket: per-daemon unix-socket command server.

Reference: src/common/admin_socket.cc -- every daemon listens on a unix
domain socket (``/var/run/ceph/<name>.asok``) and serves introspection
commands (``ceph daemon <sock> perf dump`` / ``ops`` / ``config show`` /
``help``).  Protocol here: one JSON request line ``{"prefix": ...}`` in,
one JSON document out (the reference reads a JSON command and writes a
length-prefixed JSON reply; newline-delimited keeps the same shape
without the 4-byte header).

Commands self-register like the reference's AdminSocketHook: the OSD
daemon registers ``perf dump`` (PerfCounters), ``ops`` /
``dump_historic_ops`` (OpTracker), ``config show`` / ``config set``
(md_config) and ``status``.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Callable, Dict, Optional


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._hooks: Dict[str, Callable[[dict], object]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.register("help", lambda cmd: sorted(self._hooks))

    def register(self, prefix: str, hook: Callable[[dict], object]) -> None:
        """AdminSocket::register_command; hook(cmd_dict) -> JSON-able
        (or an awaitable of one -- async hooks are awaited in the serve
        loop, so introspection commands may take the daemon's locks
        through the normal async surface)."""
        self._hooks[prefix] = hook

    async def start(self) -> str:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        try:
            os.unlink(self.path)  # stale socket from a crashed daemon
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(
            self._serve, path=self.path
        )
        return self.path

    async def stop(self) -> None:
        # swap-then-await: claim the server synchronously so concurrent
        # stop() calls cannot both pass the None check and one of them
        # close a server the other is still awaiting on
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            line = await reader.readline()
            try:
                cmd = json.loads(line.decode() or "{}")
            except json.JSONDecodeError:
                cmd = {"prefix": line.decode().strip()}
            prefix = cmd.get("prefix", "")
            hook = self._hooks.get(prefix)
            if hook is None:
                out = {"error": f"unknown command {prefix!r}",
                       "commands": sorted(self._hooks)}
            else:
                try:
                    out = hook(cmd)
                    if asyncio.iscoroutine(out) or \
                            isinstance(out, asyncio.Future):
                        out = await out
                except Exception as e:  # noqa: BLE001 -- a hook crash
                    out = {"error": f"{type(e).__name__}: {e}"}
            writer.write(json.dumps(out).encode() + b"\n")
            await writer.drain()
        finally:
            writer.close()


async def admin_command(path: str, prefix: str, **fields):
    """Client side (the ``ceph daemon <sock> <cmd>`` role).  The read
    limit is raised well past asyncio's 64 KiB default: one-line JSON
    replies grow with the daemon (the prometheus exposition and the
    profiler's speedscope dump both cross 64 KiB on a busy daemon)."""
    reader, writer = await asyncio.open_unix_connection(
        path, limit=64 << 20)
    writer.write(json.dumps(dict(fields, prefix=prefix)).encode() + b"\n")
    await writer.drain()
    line = await reader.readline()
    writer.close()
    return json.loads(line.decode())
