"""Lockdep: lock-order cycle detection for the asyncio data path.

Reference: src/common/lockdep.h:20-25 -- the reference registers every
Mutex by name, records the acquisition-order graph, and aborts on a
cycle (a potential deadlock) the FIRST time the bad order happens, not
the unlucky time both tasks interleave.  The asyncio engine has the
same hazard class (await points interleave tasks holding asyncio.Locks:
object locks, extent pins, clone/head nesting), so the rail is the
same: ``TrackedLock`` wraps asyncio.Lock, tracks per-task held sets,
adds class-order edges on each acquisition, and raises ``LockdepError``
on a cycle.

Lock *classes* (the dedup key) are the names passed in; per-object
locks share a class with a hierarchy suffix ("object:head" vs
"object:clone") so the legitimate head->clone nesting is one edge while
the reverse order is flagged.  Enabled via the ``lockdep`` config
option (like the reference's lockdep=true); zero overhead when off.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Set


class LockdepError(RuntimeError):
    """A lock-order cycle (potential deadlock) was detected."""


#: acquisition-order edges: held-class -> {acquired-classes}
_order: Dict[str, Set[str]] = {}
#: per-task held lock classes (keyed by id(task))
_held: Dict[int, List[str]] = {}


def _reaches(src: str, dst: str, seen=None) -> bool:
    if src == dst:
        return True
    seen = seen or set()
    for nxt in _order.get(src, ()):
        if nxt not in seen:
            seen.add(nxt)
            if _reaches(nxt, dst, seen):
                return True
    return False


def clear() -> None:
    """Reset the global order graph (tests)."""
    _order.clear()
    _held.clear()


def enabled() -> bool:
    try:
        from ceph_tpu.utils.config import get_config

        return bool(get_config().get_val("lockdep"))
    except KeyError:
        return False


class TrackedLock:
    """asyncio.Lock with lockdep order tracking (common/Mutex + lockdep
    registration role)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = asyncio.Lock()

    def _task_key(self) -> int:
        t = asyncio.current_task()
        return id(t) if t is not None else 0

    async def __aenter__(self):
        key = self._task_key()
        held = _held.setdefault(key, [])
        for h in held:
            if h == self.name:
                raise LockdepError(
                    f"recursive acquisition of lock class {self.name!r}"
                )
            # adding edge h -> self; a path self -> h means some task
            # acquires them in the opposite order: cycle
            if _reaches(self.name, h):
                raise LockdepError(
                    f"lock order cycle: acquiring {self.name!r} while "
                    f"holding {h!r}, but {self.name!r} -> {h!r} order "
                    "was already recorded"
                )
            _order.setdefault(h, set()).add(self.name)
        await self._lock.acquire()
        held.append(self.name)
        return self

    async def __aexit__(self, *exc):
        key = self._task_key()
        held = _held.get(key, [])
        if self.name in held:
            held.remove(self.name)
        if not held:
            _held.pop(key, None)
        self._lock.release()
        return False

    def locked(self) -> bool:
        return self._lock.locked()
