"""In-flight + historic op tracking (OpTracker/TrackedOp equivalent).

Reference: src/common/TrackedOp.{h,cc} and the OSD admin-socket commands
``dump_ops_in_flight`` / ``dump_historic_ops`` (src/osd/OSD.cc:2188-2222).
Each tracked op records a timestamped event timeline (queued, dequeued,
sub-op sent, commit...); completed ops roll into a bounded historic ring
kept by slowest-first so the worst ops survive.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional


class TrackedOp:
    def __init__(self, tracker: "OpTracker", opid: int, desc: str):
        self._tracker = tracker
        self.opid = opid
        self.desc = desc
        #: wall clock for display only; durations/ranking use monotonic so
        #: an NTP step cannot produce negative ages or mis-rank slow ops
        self.initiated_at = time.time()
        self._t0 = time.monotonic()
        self.events: List[tuple] = [(0.0, "initiated")]
        self.finished_at: Optional[float] = None
        self._t_end: Optional[float] = None

    def mark_event(self, name: str) -> None:
        self.events.append((time.monotonic() - self._t0, name))

    def finish(self) -> None:
        if self.finished_at is None:
            self.finished_at = time.time()
            self._t_end = time.monotonic()
            self.events.append((self._t_end - self._t0, "done"))
            self._tracker._finish(self)

    @property
    def duration(self) -> float:
        end = self._t_end if self._t_end is not None else time.monotonic()
        return end - self._t0

    def to_dict(self) -> dict:
        return {
            "opid": self.opid,
            "description": self.desc,
            "initiated_at": self.initiated_at,
            "age": self.duration,
            "type_data": {
                "events": [
                    {"time": self.initiated_at + t, "event": name}
                    for t, name in self.events
                ]
            },
        }


class OpTracker:
    def __init__(self, history_size: int = 20, history_slow_size: int = 20):
        self._lock = threading.Lock()
        self._next_id = 0
        self._inflight: Dict[int, TrackedOp] = {}
        self._historic: deque = deque(maxlen=history_size)
        #: slowest completed ops, kept sorted by duration
        self._slowest: List[TrackedOp] = []
        self.history_slow_size = history_slow_size

    def create_request(self, desc: str) -> TrackedOp:
        with self._lock:
            self._next_id += 1
            op = TrackedOp(self, self._next_id, desc)
            self._inflight[op.opid] = op
            return op

    def _finish(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op.opid, None)
            self._historic.append(op)
            self._slowest.append(op)
            self._slowest.sort(key=lambda o: -o.duration)
            del self._slowest[self.history_slow_size :]

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.to_dict() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [op.to_dict() for op in self._historic]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(self) -> dict:
        with self._lock:
            ops = [op.to_dict() for op in self._slowest]
        return {"num_ops": len(ops), "ops": ops}
