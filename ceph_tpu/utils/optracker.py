"""In-flight + historic op tracking (OpTracker/TrackedOp equivalent).

Reference: src/common/TrackedOp.{h,cc} and the OSD admin-socket commands
``dump_ops_in_flight`` / ``dump_historic_ops`` /
``dump_historic_slow_ops`` (src/osd/OSD.cc:2188-2222).  Each tracked op
records a timestamped event timeline (queued, dequeued, sub-op sent,
commit...); completed ops roll into a bounded historic ring kept by
slowest-first so the worst ops survive.

Since round 16 a TrackedOp carries a trace span (utils/trace.py): its
events ARE the span's timeline, so ``dump_historic_ops`` returns the
same decomposed queue-wait / batch-encode (amortized) / wire / ack /
commit segments the trace collector stitches across daemons.  Ops
slower than ``osd_op_complaint_time`` log a slow-op warning WITH that
decomposition (the "where did this one op spend its time" forensic the
aggregate bench numbers cannot answer) and are counted + retained for
``dump_historic_slow_ops``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ceph_tpu.utils import trace

log = logging.getLogger("ceph_tpu.optracker")


def _cfg_val(name: str, default):
    try:
        from ceph_tpu.utils.config import get_config

        return get_config().get_val(name)
    except Exception:  # noqa: BLE001 -- tracking must never fail an op
        return default


class TrackedOp:
    def __init__(self, tracker: "OpTracker", opid: int, desc: str,
                 span=None, t0: Optional[float] = None):
        self._tracker = tracker
        self.opid = opid
        self.desc = desc
        #: wall clock for display only; durations/ranking use monotonic so
        #: an NTP step cannot produce negative ages or mis-rank slow ops.
        #: ``t0`` backdates initiation to queue entry (queue wait is part
        #: of the op's life without allocating a TrackedOp per enqueue)
        self.initiated_at = time.time()
        self._t0 = t0 if t0 is not None else time.monotonic()
        self.events: List[tuple] = [(0.0, "initiated")]
        self.finished_at: Optional[float] = None
        self._t_end: Optional[float] = None
        #: the op's trace span (trace.NULL_SPAN when unsampled): events
        #: mirror into it so the span timeline IS the op timeline
        self.span = span if span is not None else trace.NULL_SPAN

    def mark_event(self, name: str, t: Optional[float] = None) -> None:
        """Timestamped event; ``t`` backdates (a monotonic stamp taken
        before this op object existed, e.g. queue entry)."""
        stamp = t if t is not None else time.monotonic()
        self.events.append((stamp - self._t0, name))
        self.span.event(name, t=stamp)

    def finish(self) -> None:
        if self.finished_at is None:
            self.finished_at = time.time()
            self._t_end = time.monotonic()
            self.events.append((self._t_end - self._t0, "done"))
            self.span.finish()
            self._tracker._finish(self)

    @property
    def duration(self) -> float:
        end = self._t_end if self._t_end is not None else time.monotonic()
        return end - self._t0

    def timeline(self) -> dict:
        """Decomposed per-stage latency segments (trace.op_timeline on
        the span when sampled, raw event deltas otherwise) -- segments
        sum to the op's end-to-end duration by construction."""
        if self.span.sampled:
            return trace.op_timeline(self.span)
        total = self.duration
        points = sorted(self.events) + [(total, "end")]
        segments = []
        for (t0, a), (t1, b) in zip(points, points[1:]):
            ms = max(0.0, (t1 - t0) * 1000)
            if ms > 0:
                segments.append(
                    {"segment": f"{a}->{b}", "ms": round(ms, 6)})
        return {"total_ms": round(total * 1000, 6), "segments": segments}

    def to_dict(self) -> dict:
        out = {
            "opid": self.opid,
            "description": self.desc,
            "initiated_at": self.initiated_at,
            "age": self.duration,
            "type_data": {
                "events": [
                    {"time": self.initiated_at + t, "event": name}
                    for t, name in self.events
                ]
            },
        }
        if self.span.sampled:
            out["trace_id"] = self.span.trace_id
            out["span_id"] = self.span.span_id
            out["timeline"] = self.timeline()
        return out


class OpTracker:
    def __init__(self, history_size: Optional[int] = None,
                 history_slow_size: Optional[int] = None, perf=None,
                 name: str = ""):
        if history_size is None:
            history_size = int(_cfg_val("osd_op_history_size", 20))
        if history_slow_size is None:
            history_slow_size = int(
                _cfg_val("osd_op_history_slow_size", 20))
        self._lock = threading.Lock()
        self._next_id = 0
        self._inflight: Dict[int, TrackedOp] = {}
        self._historic: deque = deque(maxlen=history_size)
        #: slowest completed ops, kept sorted by duration
        self._slowest: List[TrackedOp] = []
        self.history_slow_size = history_slow_size
        #: optional PerfCounters for the slow_ops counter
        self.perf = perf
        self.name = name
        self.slow_ops = 0

    def create_request(self, desc: str, span=None,
                       t0: Optional[float] = None) -> TrackedOp:
        with self._lock:
            self._next_id += 1
            op = TrackedOp(self, self._next_id, desc, span=span, t0=t0)
            self._inflight[op.opid] = op
            return op

    def complaint_time(self) -> float:
        return float(_cfg_val("osd_op_complaint_time", 5.0))

    def _finish(self, op: TrackedOp) -> None:
        with self._lock:
            self._inflight.pop(op.opid, None)
            self._historic.append(op)
            slowest = self._slowest
            # only contenders pay the sort (most finishes are fast ops
            # below the retained floor -- this runs per op)
            if len(slowest) < self.history_slow_size or \
                    op.duration > slowest[-1].duration:
                slowest.append(op)
                slowest.sort(key=lambda o: -o.duration)
                del slowest[self.history_slow_size :]
        complaint = self.complaint_time()
        if complaint > 0 and op.duration >= complaint:
            self._note_slow(op, complaint)

    def _note_slow(self, op: TrackedOp, complaint: float) -> None:
        """Slow-op forensics: count it and log the full decomposed
        timeline (the reference's cluster-log 'slow request' complaint,
        upgraded with per-stage attribution)."""
        self.slow_ops += 1
        if self.perf is not None:
            self.perf.inc("slow_ops")
        tl = op.timeline()
        segs = ", ".join(
            f"{s['segment']}={s['ms']:.1f}ms" for s in tl.get(
                "segments", []))
        log.warning(
            "slow op%s: %s took %.3fs (complaint %.3fs): %s",
            f" [{self.name}]" if self.name else "", op.desc,
            op.duration, complaint, segs or "no timeline recorded",
        )

    def num_inflight(self) -> int:
        """In-flight op count without rendering op dicts (the per-report
        gauge: dump_ops_in_flight builds a full description per op)."""
        with self._lock:
            return len(self._inflight)

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.to_dict() for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [op.to_dict() for op in self._historic]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_slow_ops(self) -> dict:
        """Slowest retained ops that crossed osd_op_complaint_time
        (worst-first; falls back to the slowest ring when nothing
        crossed -- the operator asked 'show me the worst')."""
        complaint = self.complaint_time()
        with self._lock:
            slow = [op for op in self._slowest
                    if complaint > 0 and op.duration >= complaint]
            ops = [op.to_dict() for op in (slow or self._slowest)]
        return {"num_ops": len(ops), "ops": ops,
                "complaint_time": complaint,
                "slow_ops_counted": self.slow_ops}
