"""Leveled per-subsystem logging with an in-memory ring (dout / log::Log
equivalents).

Reference: src/common/dout.h gated `dout(n)` macros per subsystem
(src/log/SubsystemMap.h), async writer with a recent-entries ring kept for
crash dumps (src/log/Log.cc).  Here: ``dout(subsys, level)`` checks the
config's debug_<subsys> gather level; entries go to a bounded ring and,
above the stderr threshold, to stderr.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Deque, Tuple

from ceph_tpu.utils.config import get_config

_RING_SIZE = 10000
_ring: Deque[Tuple[float, str, int, str]] = collections.deque(maxlen=_RING_SIZE)
_lock = threading.Lock()
_stderr_level = 0  # entries at level <= this also print


def set_stderr_level(level: int) -> None:
    global _stderr_level
    _stderr_level = level


def should_gather(subsys: str, level: int) -> bool:
    try:
        return level <= get_config().get_val(f"debug_{subsys}")
    except KeyError:
        return False


def dout(subsys: str, level: int, message: str) -> None:
    if not should_gather(subsys, level):
        return
    entry = (time.time(), subsys, level, message)
    with _lock:
        _ring.append(entry)
    if level <= _stderr_level:
        print(f"[{subsys}:{level}] {message}", file=sys.stderr)


def derr(subsys: str, message: str) -> None:
    entry = (time.time(), subsys, -1, message)
    with _lock:
        _ring.append(entry)
    print(f"[{subsys}:ERR] {message}", file=sys.stderr)


def recent_entries(count: int = 100):
    """Crash-dump view of the in-memory ring (log::Log::dump_recent role)."""
    with _lock:
        return list(_ring)[-count:]
