"""Typed configuration schema + runtime config (options.cc / md_config_t
equivalents).

Reference: src/common/options.cc declares every option once with type,
default, level and description; src/common/config.cc layers conf-file /
env / CLI / runtime overrides with change observers.  Same shape here:
a single OPTIONS schema, a Config that validates against it, observer
callbacks on apply_changes, and typed get_val access.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, List, Optional

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


@dataclasses.dataclass
class Option:
    name: str
    type: type
    default: Any
    level: str = LEVEL_ADVANCED
    description: str = ""
    see_also: tuple = ()


def _opt(name, typ, default, level=LEVEL_ADVANCED, desc="", see_also=()):
    return Option(name, typ, default, level, desc, see_also)


#: the schema (reference: src/common/options.cc; EC-relevant subset + ours)
OPTIONS: Dict[str, Option] = {
    o.name: o
    for o in [
        _opt("erasure_code_dir", str, "", LEVEL_ADVANCED,
             "directory for out-of-tree erasure code plugins"),
        _opt("osd_erasure_code_plugins", str, "jerasure lrc isa tpu",
             LEVEL_ADVANCED, "plugins preloaded at daemon start"),
        _opt("osd_pool_default_erasure_code_profile", str,
             "plugin=jerasure technique=reed_sol_van k=2 m=1",
             LEVEL_ADVANCED, "default EC profile for new pools"),
        _opt("ec_backend", str, "auto", LEVEL_BASIC,
             "codec compute backend: auto|cpu|native|tpu"),
        _opt("ec_tpu_tile", int, 4096, LEVEL_DEV,
             "pallas kernel lane tile (int32 lanes)"),
        _opt("ec_batch_stripes", int, 64, LEVEL_ADVANCED,
             "stripes fused per device dispatch in the batching shim"),
        _opt("osd_ec_op_coalesce", bool, True, LEVEL_ADVANCED,
             "gather concurrent client-op EC codec work into batched "
             "dispatches (the per-PG encode/decode coalescer; client "
             "ops only, recovery/scrub stay per-call)"),
        _opt("osd_ec_donate", bool, True, LEVEL_ADVANCED,
             "donate the packed encode granule's device buffer to XLA "
             "(jit donate_argnums): encode stops double-holding the "
             "input in HBM and skips the content-hash of the upload "
             "cache.  Donation and content-addressed upload caching "
             "are mutually exclusive retention modes -- set false to "
             "restore the cached-upload behavior (re-encoding "
             "byte-identical content then elides the H2D again)",
             see_also=("osd_tier_h2d_cache_bytes", "no_h2d_cache")),
        _opt("osd_ec_shape_rungs", str, "", LEVEL_ADVANCED,
             "batch-shape bucketing ladder for the persistent encode "
             "pipeline: comma/space-separated byte rungs (ascending); "
             "batches pad up to the smallest fitting rung so steady "
             "state runs at zero XLA retraces (ops/bucketing.py).  "
             "Empty = the built-in 16KiB..16MiB power-of-two ladder"),
        _opt("osd_ec_overlap_depth", int, 2, LEVEL_ADVANCED,
             "encode pipeline H2D/compute overlap slots: granule N+1's "
             "packed upload is issued while up to this many earlier "
             "granules are still in the GF matmul (double-buffering at "
             "2).  1 restores upload-then-compute-in-lockstep; the "
             "in-flight D2H depth is bounded separately"),
        _opt("osd_tier_promote_from_encode", bool, True, LEVEL_ADVANCED,
             "hand the cache tier the still-device-resident encode "
             "output when a written object should be hot (writeback "
             "promote-on-write composes the [k+m, shard] block ON "
             "device from the granule input and parity output: zero "
             "re-upload) instead of re-uploading the host copy.  "
             "Granules carrying such objects are never donated",
             see_also=("osd_ec_donate", "osd_tier_promote_temp")),
        _opt("osd_mesh_data_plane", bool, False, LEVEL_ADVANCED,
             "mesh-shard the OSD data plane over the local "
             "jax.sharding.Mesh (ceph_tpu/parallel/mesh_plane.py): PG "
             "ownership is sliced over the mesh's pg axis, the per-PG "
             "coalescer's fused encode batches run SPMD across the "
             "devices, and chunk payloads destined for in-mesh OSDs "
             "are delivered through the device plane (in-collective) "
             "instead of being serialized through the TCP messenger.  "
             "False (default) keeps the single-device path -- the A/B "
             "baseline the mesh-path bench compares against",
             see_also=("osd_mesh_devices", "osd_mesh_scatter",
                       "osd_mesh_board_bytes")),
        _opt("osd_mesh_devices", int, 0, LEVEL_ADVANCED,
             "devices the mesh data plane spans (0 = every local jax "
             "device).  Each mesh device hosts one OSD's PG-shard "
             "slice; OSDs past the device count stay out-of-mesh and "
             "keep the wire delivery path",
             see_also=("osd_mesh_data_plane",)),
        _opt("osd_mesh_scatter", str, "auto", LEVEL_ADVANCED,
             "in-collective parity scatter mode for the mesh data "
             "plane: 'auto' shards the GF contraction over the mesh's "
             "shard axis (psum_scatter parity placement) only on a TPU "
             "backend where the collectives ride ICI; 'on' forces it "
             "(cpu-fallback correctness runs); 'off' keeps every "
             "device's encode mesh-local (pg slicing only)",
             see_also=("osd_mesh_data_plane",)),
        _opt("osd_mesh_board_bytes", int, 64 << 20, LEVEL_ADVANCED,
             "byte bound on the mesh delivery board (the in-collective "
             "chunk handoff between in-mesh OSDs): beyond it the "
             "oldest unclaimed deposits are dropped and the affected "
             "sub-write fails over to normal recovery (bounded memory; "
             "claims release immediately)",
             see_also=("osd_mesh_data_plane",)),
        _opt("osd_recovery_max_chunk", int, 8 << 20, LEVEL_ADVANCED,
             "max bytes per recovery window"),
        _opt("osd_recovery_batched", bool, True, LEVEL_ADVANCED,
             "route recovery pushes through the batched background data "
             "plane (osd/recovery.py): per-PG recovery coalescer, fused "
             "decode dispatches, corked multi-push messenger bursts.  "
             "False restores the per-object windowed path (kept as the "
             "recovery-path bench baseline)",
             see_also=("osd_recovery_batch_bytes",
                       "osd_recovery_max_active")),
        _opt("osd_recovery_batch_bytes", int, 8 << 20, LEVEL_ADVANCED,
             "byte budget per batched recovery dispatch: a batch's "
             "gathered source chunks stay under this, and an object "
             "whose shards exceed the per-object share falls back to "
             "the windowed per-object path (bounded primary memory)",
             see_also=("osd_recovery_batched",)),
        _opt("osd_ec_fractional_repair", bool, True, LEVEL_ADVANCED,
             "let fractional-repair codecs (regenerating codes, plugin "
             "'regen') rebuild a single lost shard from beta-sized "
             "helper symbols instead of k whole chunks.  False forces "
             "the classic full-stripe gather (kept as the repair-path "
             "bench baseline)",
             see_also=("osd_recovery_batched",)),
        _opt("osd_recovery_sleep", float, 0.0, LEVEL_ADVANCED,
             "seconds of awaited pacing between background recovery/"
             "scrub batches (the osd_recovery_sleep role); 0 still "
             "yields the event loop once per batch so client ops "
             "interleave",
             see_also=("osd_recovery_batched",)),
        _opt("osd_scrub_chunk_max", int, 512 << 10, LEVEL_ADVANCED,
             "deep-scrub read-cursor chunk bytes per shard per round: "
             "scrub walks objects in chunks of this size through the "
             "batched read lane instead of one whole-shard read per "
             "object (bounded memory, paced I/O)"),
        _opt("osd_tier_promote_on_recovery", bool, True, LEVEL_ADVANCED,
             "land a rebuilt hot (or previously-resident) object's full "
             "shard block in the device tier as part of recovery itself "
             "(promote-on-recovery): the batch already holds every "
             "chunk, so the promote costs no extra shard reads.  The "
             "insert is counted as tier_promote_from_recovery",
             see_also=("osd_tier_promote_temp",
                       "osd_tier_promote_from_encode")),
        _opt("osd_qos_unified", bool, True, LEVEL_ADVANCED,
             "fuse the dmClock op-queue discipline into the batched "
             "data plane (osd/qos.py): coalesced client batches, "
             "recovery cycles and scrub rounds claim admission slots "
             "in per-class reservation/weight/limit tag order with "
             "cost = stripe bytes, replacing the round-14 "
             "BackgroundThrottle's client-pressure preemption gauge.  "
             "False restores the gauge-based preemption (the A/B "
             "baseline)",
             see_also=("osd_qos_profile", "osd_qos_slots")),
        _opt("osd_qos_profile", str,
             "client:0:100:0,recovery:4:10:0,scrub:1:5:0",
             LEVEL_ADVANCED,
             "per-class dmClock triple, comma/space-separated "
             "name:reservation:weight:limit entries -- reservation and "
             "limit in MiB/s (0 = none), weight unitless.  Applied by "
             "the unified admission layer (cost = batch stripe bytes) "
             "and, scaled to 4KiB cost units, by the mclock op queue "
             "for client sub-classes (a client op's qos_class field "
             "names one)",
             see_also=("osd_qos_unified",)),
        _opt("osd_qos_slots", int, 4, LEVEL_ADVANCED,
             "concurrent admission slots for batched dispatches per "
             "OSD: the unified QoS layer's service capacity -- when "
             "all are busy, freed slots go to queued classes in "
             "dmClock tag order (the point where reservation floors "
             "and weight shares are enforced)",
             see_also=("osd_qos_unified",)),
        _opt("osd_qos_op_slots", int, 64, LEVEL_ADVANCED,
             "concurrent client-op execution slots per OSD under "
             "unified QoS (the osd_op_tp width): freed slots are "
             "granted to queued client ops in dmClock tag order by "
             "qos_class instead of semaphore FIFO.  Matches the "
             "legacy _cop_sem width by default",
             see_also=("osd_qos_unified", "osd_qos_slots")),
        _opt("loadgen_client_inflight", int, 4, LEVEL_ADVANCED,
             "per-client in-flight op budget in the load generator "
             "(ceph_tpu/loadgen/): an open-loop client whose arrivals "
             "outrun completions parks on this semaphore instead of "
             "accumulating unbounded tasks, so a million-client run "
             "cannot OOM the harness; the high-water mark is surfaced "
             "as client_inflight_hwm"),
        _opt("osd_pg_log_dups_tracked", int, 3000, LEVEL_ADVANCED,
             "reqid dup entries retained per OSD PG log for client-op "
             "replay detection; kept past trim() like the reference's "
             "pg_log_dup_t list (src/osd/osd_types.h), evicted oldest "
             "first past this bound"),
        _opt("client_probe_retries", int, 2, LEVEL_ADVANCED,
             "consecutive failed probes of an unresponsive primary "
             "before the Objecter demotes it and fails the op over "
             "(the osd_heartbeat_grace role on the client side; one "
             "missed connect under host load must not demote a live "
             "primary)"),
        _opt("client_probe_grace", float, 1.0, LEVEL_ADVANCED,
             "seconds per Objecter reply-wait slice and per probe "
             "attempt while an op is in flight",
             see_also=("client_probe_retries",)),
        _opt("client_backoff_base", float, 0.05, LEVEL_ADVANCED,
             "initial delay before an Objecter resend after a primary "
             "failover; doubles per attempt (with jitter) up to "
             "client_backoff_max, always capped by the op deadline"),
        _opt("client_backoff_max", float, 2.0, LEVEL_ADVANCED,
             "ceiling on the Objecter's exponential resend backoff",
             see_also=("client_backoff_base",)),
        _opt("osd_recovery_max_active", int, 3, LEVEL_ADVANCED,
             "max concurrent object recoveries per OSD"),
        _opt("osd_tick_interval", float, 5.0, LEVEL_ADVANCED,
             "seconds between OSD background ticks (peering/scrub)"),
        _opt("trace_mode", str, "sampled", LEVEL_ADVANCED,
             "end-to-end op tracing (utils/trace.py): 'off' mints no "
             "spans; 'sampled' traces one in trace_sample_every root "
             "ops (the default: forensics always on at negligible "
             "cost, gated by the bench tracing stage); 'full' traces "
             "every op.  The sampling decision travels with the wire "
             "context, so a trace is always whole",
             see_also=("trace_sample_every", "trace_keep")),
        _opt("trace_sample_every", int, 64, LEVEL_ADVANCED,
             "in sampled trace_mode, one of this many root ops is "
             "traced (client ops and background batches roll "
             "independently)",
             see_also=("trace_mode",)),
        _opt("trace_keep", int, 256, LEVEL_ADVANCED,
             "finished spans retained in the bounded collector ring "
             "(oldest dropped and counted; the seed's unbounded "
             "_finished list is gone)",
             see_also=("trace_keep_slow",)),
        _opt("trace_keep_slow", int, 64, LEVEL_ADVANCED,
             "slowest finished root spans retained past ring churn "
             "(the optracker historic-slow discipline)",
             see_also=("trace_keep",)),
        _opt("profile_mode", str, "off", LEVEL_ADVANCED,
             "wire-tax profiler (ceph_tpu/profiling/): 'off' (default "
             "-- instrumented seams cost one branch, allocate nothing), "
             "'on' (stage cost ledger + event-loop/GC arms; the <=3%-"
             "overhead configuration the bench wire_tax stage gates), "
             "'full' ('on' plus the continuous stack sampler for "
             "speedscope/flamegraph export)",
             see_also=("profile_sample_hz", "profile_topk")),
        _opt("profile_sample_hz", float, 87.0, LEVEL_ADVANCED,
             "stack-sampler frequency in profile_mode=full (off the "
             "round numbers so it cannot phase-lock with periodic "
             "work)",
             see_also=("profile_mode",)),
        _opt("profile_topk", int, 20, LEVEL_ADVANCED,
             "slow-callback and stage rows returned by the profile "
             "admin-socket/CLI views",
             see_also=("profile_mode",)),
        _opt("osd_op_complaint_time", float, 5.0, LEVEL_ADVANCED,
             "an op slower than this logs a slow-op warning with its "
             "full decomposed timeline and is retained by "
             "dump_historic_slow_ops (reference "
             "osd_op_complaint_time, 30s; shrunk to the mini-cluster "
             "time scale)"),
        _opt("osd_op_history_size", int, 20, LEVEL_ADVANCED,
             "completed TrackedOps retained per daemon for "
             "dump_historic_ops (reference osd_op_history_size)"),
        _opt("osd_op_history_slow_size", int, 20, LEVEL_ADVANCED,
             "slowest completed TrackedOps retained per daemon "
             "(reference osd_op_history_slow_op_size)"),
        _opt("lockdep", bool, False, LEVEL_DEV,
             "track lock acquisition order and raise on cycles "
             "(reference src/common/lockdep.h; asyncio-lock analogue)"),
        _opt("mgr_modules", str, "status prometheus", LEVEL_BASIC,
             "mgr modules loaded at start: bare names resolve under "
             "ceph_tpu.mgr.mgr_modules, dotted paths import third-party "
             "modules (reference: mgr_initial_modules)"),
        _opt("osd_client_op_commit_timeout", float, 30.0, LEVEL_ADVANCED,
             "seconds a primary waits for sub-write commit acks before "
             "failing the op (fault-injection tests shrink this to "
             "manufacture torn writes)"),
        _opt("osd_read_gather_timeout", float, 15.0, LEVEL_ADVANCED,
             "seconds a primary waits for sub-read replies before "
             "serving with whatever arrived (degraded decode or EIO)"),
        _opt("osd_scrub_objects_per_tick", int, 4, LEVEL_ADVANCED,
             "deep-scrub at most this many objects per background tick "
             "(rate limit; 0 disables background scrub)"),
        _opt("osd_client_message_size_cap", int, 500 * 1024 * 1024,
             LEVEL_ADVANCED,
             "max bytes of in-flight inbound messages a daemon holds "
             "before back-pressuring senders (dispatch throttle)"),
        _opt("osd_heartbeat_interval", float, 1.0, LEVEL_ADVANCED,
             "seconds between OSD peer heartbeat rounds (reference "
             "osd_heartbeat_interval, src/osd/OSD.cc heartbeat())"),
        _opt("osd_heartbeat_grace", float, 4.0, LEVEL_ADVANCED,
             "seconds of heartbeat silence before an OSD reports a peer "
             "failed to the mon (reference osd_heartbeat_grace; shrunk "
             "here to match the mini-cluster's time scale)"),
        _opt("mon_mgr_beacon_grace", float, 30.0, LEVEL_ADVANCED,
             "seconds of mgr-beacon silence before a standby's beacon "
             "triggers failover (reference mon_mgr_beacon_grace)"),
        _opt("mgr_beacon_interval", float, 0.25, LEVEL_ADVANCED,
             "seconds between daemon->mgr liveness beacons (the "
             "MgrClient beacon cadence; reference mgr_tick_period, "
             "shrunk to the mini-cluster time scale)",
             see_also=("mgr_daemon_beacon_grace",)),
        _opt("mgr_report_interval", float, 1.0, LEVEL_ADVANCED,
             "seconds between daemon->mgr MgrReport frames (per-PG "
             "stats, perf-counter slice, histogram marginals -- the "
             "mgr_stats_period role); consecutive reports feed the "
             "PGMap rate engine, so shrinking this sharpens the io "
             "rates at the cost of frame traffic"),
        _opt("mgr_daemon_beacon_grace", float, 2.0, LEVEL_ADVANCED,
             "seconds of beacon silence before the mgr's wire-fed map "
             "marks a daemon down (OSD_DOWN / MON_DOWN from staleness "
             "-- the mon_osd_report_timeout role, shrunk to the "
             "mini-cluster time scale)",
             see_also=("mgr_beacon_interval",)),
        _opt("mgr_pg_stale_grace", float, 4.0, LEVEL_ADVANCED,
             "seconds without a fresh per-PG report before the PGMap "
             "flags PG_STALE for that (pool, primary) slice (the "
             "reference's stale-PG detection via pg stats epochs)",
             see_also=("mgr_report_interval",)),
        _opt("mgr_lag_warn_ms", float, 250.0, LEVEL_ADVANCED,
             "event-loop lag (sampled sleep-drift EWMA, shipped in "
             "beacons/reports) at or above which a daemon counts "
             "toward the DAEMON_LAG health check",
             see_also=("mgr_lag_sustain",)),
        _opt("mgr_lag_sustain", int, 3, LEVEL_ADVANCED,
             "consecutive over-threshold beacons/reports before "
             "DAEMON_LAG fires (one GC pause must not page an "
             "operator; a saturated wire loop should)",
             see_also=("mgr_lag_warn_ms",)),
        _opt("mon_osd_min_down_reporters", int, 2, LEVEL_ADVANCED,
             "distinct OSD failure reporters required before the mon "
             "marks the target down (reference "
             "mon_osd_min_down_reporters, src/mon/OSDMonitor.cc "
             "check_failure)"),
        _opt("osd_tier_hbm_bytes", int, 256 << 20, LEVEL_ADVANCED,
             "device (HBM) byte budget for the storage layer's resident "
             "state: cache-tier shard blocks plus the batching "
             "pipeline's content-addressed H2D stripe cache.  The tier "
             "agent evicts coldest-first to stay under it "
             "(ceph_tpu/tier/device_tier.py DeviceByteAccount)"),
        _opt("osd_tier_h2d_cache_bytes", int, 64 << 20, LEVEL_ADVANCED,
             "sub-allocation of osd_tier_hbm_bytes reserved for the "
             "pipeline's content-addressed H2D stripe cache "
             "(ops/pipeline.py; replaces the old hard-coded 4-entry "
             "LRU).  The tier yields to this working set, never the "
             "other way around",
             see_also=("osd_tier_hbm_bytes", "no_h2d_cache")),
        _opt("osd_tier_promote_temp", float, 0.25, LEVEL_ADVANCED,
             "hit-set temperature at or above which the tier agent "
             "promotes an object's shards into device memory (and "
             "writeback-mode writes refresh the resident copy, "
             "promote-on-write)"),
        _opt("osd_tier_promote_max_per_tick", int, 8, LEVEL_ADVANCED,
             "max objects promoted per tier-agent tick; the whole set "
             "rides one batched gather + device transfer",
             see_also=("osd_tier_promote_temp",)),
        _opt("osd_msgr_cork", bool, True, LEVEL_ADVANCED,
             "coalesce outgoing messenger frames per connection into "
             "scatter-gather bursts (one writelines + one drain per "
             "burst) and piggyback/batch acks instead of one ack frame "
             "+ drain per message; off = one write/drain per message "
             "(the pre-round-8 wire behavior, kept as the bench "
             "baseline)"),
        _opt("osd_msgr_cork_bytes", int, 256 * 1024, LEVEL_ADVANCED,
             "corked send queue byte threshold: a queue reaching this "
             "many pending frame bytes flushes immediately instead of "
             "waiting for the end-of-tick flush",
             see_also=("osd_msgr_cork",)),
        _opt("osd_msgr_shm_ring", bool, False, LEVEL_ADVANCED,
             "carry frame bursts between mesh-colocated daemons over "
             "seqlock'd shared-memory byte rings (msg/shm_ring.py) "
             "instead of the localhost TCP hop.  The protocol above "
             "the byte transport -- banner, auth, session watermarks, "
             "cumulative acks, frame crcs, torn-burst replay -- runs "
             "unchanged; peers without a ring-registered accept "
             "endpoint fall back to TCP per connection.  False "
             "(default) keeps TCP everywhere, the A/B baseline",
             see_also=("osd_shm_ring_bytes", "osd_msgr_cork")),
        _opt("osd_shm_ring_bytes", int, 4 << 20, LEVEL_ADVANCED,
             "per-direction byte capacity of each shared-memory frame "
             "ring; a full ring back-pressures the producer's drain() "
             "exactly like a full socket buffer",
             see_also=("osd_msgr_shm_ring",)),
        _opt("osd_op_batch_exec", bool, True, LEVEL_ADVANCED,
             "execute decoded client-op bursts through the OSD shard's "
             "array-batched fast path (osd/shard.py): one optracker "
             "request, one dups-registry pass, per-class amortized QoS "
             "admission and one corked reply burst per batch instead "
             "of per-op dict walks.  Semantics (dup answers, typed "
             "errors, apply-window kills, caps) are identical; false "
             "runs the per-op path, the A/B baseline the wire-tax "
             "bench compares against",
             see_also=("osd_op_batch_max", "osd_wire_codec_native")),
        _opt("osd_op_batch_max", int, 64, LEVEL_ADVANCED,
             "max client ops gathered into one batched execution run "
             "(bounds per-batch reply latency and memory)",
             see_also=("osd_op_batch_exec",)),
        _opt("osd_wire_codec_native", bool, True, LEVEL_ADVANCED,
             "batch-encode/decode v4 frame bodies through the "
             "_wire_native C extension (ceph_tpu/native/wire_codec.py); "
             "false runs the pure-Python codec in msg/wire.py -- wire "
             "bytes are identical either way (the A/B baseline and the "
             "no-toolchain degraded mode)",
             see_also=("native", "osd_msgr_cork")),
        _opt("gc_freeze_on_start", bool, True, LEVEL_ADVANCED,
             "after daemon startup warm-up, gc.freeze() the boot-time "
             "heap into the permanent generation and raise the gen0 "
             "threshold: the r19 profiler measured gc pauses growing "
             "2.6%->11.1% of the saturated wall on a loaded heap, and "
             "the startup object graph (codecs, maps, config, jitted "
             "callables) never becomes garbage while the daemon lives"),
        _opt("ms_inject_socket_failures", int, 0, LEVEL_DEV,
             "inject a message drop roughly every N messages"),
        _opt("ms_inject_internal_delays", float, 0.0, LEVEL_DEV,
             "probability of injected message delay"),
        _opt("debug_ec", int, 0, LEVEL_DEV, "EC subsystem log level 0..20"),
        _opt("debug_osd", int, 0, LEVEL_DEV, "OSD subsystem log level 0..20"),
        _opt("debug_ms", int, 0, LEVEL_DEV, "messenger log level 0..20"),
        # -- keys below are read through the raw env layer
        # (CEPH_TPU_<NAME>) by call sites that must see runtime env
        # changes or run before a Config exists; declared here so the
        # schema stays the single source of truth (cephlint
        # ceph-config-undeclared-key enforces it) and `config show`
        # surfaces them.  Defaults mirror the call-site fallbacks.
        _opt("native", bool, True, LEVEL_DEV,
             "master toggle for the native C extensions on the wire "
             "path (CEPH_TPU_NATIVE=0 forces every codec seam to pure "
             "Python -- the no-toolchain degraded mode, log-once + "
             "ceph_wire_codec_native gauge)",
             see_also=("osd_wire_codec_native",)),
        _opt("no_h2d_cache", bool, False, LEVEL_DEV,
             "disable the device-side H2D stripe cache in the batching "
             "pipeline (ops/pipeline.py; bench.py toggles this per run "
             "to measure upload cost)"),
        _opt("cli_state", str, "", LEVEL_DEV,
             "path of the ceph CLI's persisted mini-cluster state file "
             "(tools/ceph_cli.py; empty = its per-user default)"),
        _opt("atomic_verify", bool, True, LEVEL_DEV,
             "tier-1 runtime atomic-section verifier "
             "(analysis/runtime.py via tests/conftest.py): every event "
             "loop's task factory checks that no task ever suspends "
             "inside a declared `cephlint: atomic-section` region; "
             "CEPH_TPU_ATOMIC_VERIFY=0 disables the instrumentation"),
        _opt("residency_verify", str, "1", LEVEL_DEV,
             "tier-1 runtime device-resident-section verifier "
             "(analysis/residency.py via tests/conftest.py): declared "
             "`cephlint: device-resident-section` regions run under "
             "jax.transfer_guard_device_to_host('disallow') and a seam "
             "D2H inside one raises.  Values: 1/raise (default), "
             "record (violations only fail the driving test), 0 (off; "
             "CEPH_TPU_RESIDENCY_VERIFY=0 is the escape hatch)"),
        _opt("bench_probe_timeout", float, 120.0, LEVEL_DEV,
             "seconds bench.py allows each TPU availability probe"),
        _opt("bench_retry_secs", float, 600.0, LEVEL_DEV,
             "total seconds bench.py keeps re-probing for a free TPU "
             "before falling back"),
        _opt("bench_retry_interval", float, 30.0, LEVEL_DEV,
             "seconds between bench.py TPU re-probes"),
        _opt("bench_fallback", str, "", LEVEL_DEV,
             "internal bench.py marker: set in the child process after "
             "a TPU-probe fallback so it reports the real backend"),
    ]
}


class Config:
    """Layered config with observers (md_config_t role)."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = {}
        self._observers: List[Callable[[set], None]] = []
        # env layer: CEPH_TPU_<NAME>
        for name, opt in OPTIONS.items():
            env = os.environ.get("CEPH_TPU_" + name.upper())
            if env is not None:
                self._values[name] = self._coerce(opt, env)
        if overrides:
            for key, val in overrides.items():
                self.set_val(key, val)

    @staticmethod
    def _coerce(opt: Option, value: Any):
        if opt.type is bool and isinstance(value, str):
            return value.lower() in ("1", "true", "yes")
        return opt.type(value)

    def get_val(self, name: str):
        opt = OPTIONS.get(name)
        if opt is None:
            raise KeyError(f"no such option: {name}")
        with self._lock:
            return self._values.get(name, opt.default)

    def set_val(self, name: str, value) -> None:
        opt = OPTIONS.get(name)
        if opt is None:
            raise KeyError(f"no such option: {name}")
        with self._lock:
            self._values[name] = self._coerce(opt, value)

    def add_observer(self, fn: Callable[[set], None]) -> None:
        self._observers.append(fn)

    def apply_changes(self, changes: Dict[str, Any]) -> None:
        changed = set()
        for key, val in changes.items():
            self.set_val(key, val)
            changed.add(key)
        for fn in list(self._observers):  # snapshot: observers may
            fn(changed)                   # self-remove when their owner
                                          # was garbage-collected

    def show_config(self) -> Dict[str, Any]:
        return {name: self.get_val(name) for name in sorted(OPTIONS)}


_global: Optional[Config] = None
_global_lock = threading.Lock()


def get_config() -> Config:
    global _global
    with _global_lock:
        if _global is None:
            _global = Config()
        return _global
