"""GC tuning for long-lived daemons (the round-19 gc-pause-tax fix).

The wire-tax profiler's loop/GC arm measured collector pauses growing
from 2.6% of the saturated wall on a clean heap to 11.1% on a loaded
one (PERF_NOTES r19): CPython's generational collector re-traces the
whole boot-time object graph -- codec tables, osdmaps, config, jitted
callables, placement caches -- on every full collection, and that graph
only grows with uptime while never becoming garbage.

:func:`freeze_after_warmup` is called by the daemon entrypoints
(daemon/{osd,mon,mgr}.py) once startup is complete, gated by the
``gc_freeze_on_start`` option:

* ``gc.collect()`` first, so actual boot garbage is reclaimed rather
  than frozen forever;
* ``gc.freeze()`` moves every surviving object into the permanent
  generation -- full collections stop scaling with the boot heap;
* the gen0 threshold rises (700 -> 50k) so the remaining op-scoped
  young-generation churn triggers fewer, not longer, pauses -- the
  surviving young objects per threshold window are bounded by the
  op working set either way.

The improvement is pinned by a profiler-backed test
(tests/test_wire_native.py::test_gc_freeze_shrinks_collect_pause) that
measures a full collection over a loaded heap before and after freeze.
"""

from __future__ import annotations

import gc
from typing import Optional

#: thresholds for a frozen daemon heap: young-gen churn is op-scoped,
#: so a higher gen0 trigger amortizes pause COUNT without growing any
#: single pause's traced set
FROZEN_THRESHOLDS = (50_000, 25, 25)

_frozen = False
_prior_thresholds: Optional[tuple] = None


def freeze_after_warmup(force: bool = False) -> bool:
    """Freeze the warm daemon heap; returns whether it was applied
    (False when ``gc_freeze_on_start`` is off and ``force`` unset)."""
    global _frozen, _prior_thresholds
    if not force:
        from ceph_tpu.utils.config import get_config

        try:
            if not bool(get_config().get_val("gc_freeze_on_start")):
                return False
        except KeyError:
            return False
    gc.collect()
    gc.freeze()
    if _prior_thresholds is None:
        _prior_thresholds = gc.get_threshold()
    gc.set_threshold(*FROZEN_THRESHOLDS)
    _frozen = True
    return True


def unfreeze() -> None:
    """Undo :func:`freeze_after_warmup` (test isolation: the freeze is
    process-global state)."""
    global _frozen, _prior_thresholds
    gc.unfreeze()
    if _prior_thresholds is not None:
        gc.set_threshold(*_prior_thresholds)
        _prior_thresholds = None
    _frozen = False


def status() -> dict:
    """Freeze state for the admin/observability surface."""
    return {
        "frozen": _frozen,
        "permanent_objects": gc.get_freeze_count(),
        "thresholds": list(gc.get_threshold()),
    }
